"""Pod-shaped virtual-mesh scale for the full dryrun (VERDICT r4 item 3).

``dryrun_multichip`` exercises every data-plane program — terasort
narrow+wide, wordcount, ring/Ulysses attention, TileExchange rounds,
joins, aggregation, external sort, the windowed record plane, and the
bulk session — over an n-device mesh.  The driver runs it at 8; this
test runs it at 16 in a subprocess (fresh backend, so the forced
device count takes), covering the regime where the plan matrices (E²
lengths), window cutter, and tile rounds grow beyond the default mesh
(reference full-mesh warm-up analog, RdmaShuffleManager.scala:70-118).

Set ``SPARKRDMA_DRYRUN_DEVICES`` to override (e.g. 32 — verified green
2026-07-31, see MULTICHIP_SCALE.json; ~6 min on the 1-core builder, so
the in-suite default stays 16).
"""

import os
import subprocess
import sys


def test_dryrun_multichip_16_devices():
    n = int(os.environ.get("SPARKRDMA_DRYRUN_DEVICES", "16"))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n}); "
         f"print('DRYRUN{n} OK')"],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env=env, capture_output=True, text=True, timeout=1800,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"DRYRUN{n} OK" in proc.stdout, proc.stdout


def test_fabric_256_peers_bounded_by_conf_on_both_engines():
    """The pooled-fabric acceptance (ROADMAP item 1 / RDMAvisor
    direction): ONE node fetches striped blocks from 256+ simulated
    peers through the bounded fabric — fds, transport threads, and
    cached channels must all stay bounded by CONF (cache cap / lane
    pool / O(1) dispatcher), not O(peers × stripes), on BOTH transport
    engines, with payloads bit-exact through the eviction churn."""
    import threading
    import time

    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.transport.node import Node, transport_census
    from sparkrdma_tpu.transport.simfleet import SimPeerFleet
    from sparkrdma_tpu.utils.types import BlockLocation

    n_peers = int(os.environ.get("SPARKRDMA_FABRIC_PEERS", "256"))
    cap = 8
    pattern = (np.arange(2 << 20, dtype=np.uint32) % 251).astype(np.uint8)
    prev_metrics = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True

    def read_one(node, connect, peer, loc, timeout=60):
        done = threading.Event()
        res = {}
        node.get_read_group(peer, connect).read_blocks(
            [loc],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
        )
        assert done.wait(timeout), f"fetch from {peer} hung"
        assert "ok" in res, res.get("error")
        got = res["ok"][0]
        got = got if isinstance(got, np.ndarray) else np.frombuffer(
            memoryview(got), np.uint8)
        assert np.array_equal(
            got, pattern[loc.address:loc.address + loc.length]
        ), f"corrupt payload from {peer}"

    try:
        for engine, fleet_base, node_port in (
            ("off", 28000, 28990),
            ("on", 28000 + n_peers + 16, 28991),
        ):
            # settle threads left by the previous engine's teardown
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                before = transport_census()
                if before["by_role"].get("tcp", 0) == 0:
                    break
                time.sleep(0.05)
            fleet = SimPeerFleet(n_peers, fleet_base, pattern)
            conf = TpuShuffleConf({
                "spark.shuffle.tpu.transportAsyncDispatcher": engine,
                "spark.shuffle.tpu.transportMaxCachedChannels": cap,
                "spark.shuffle.tpu.transportLanePoolSize": 4,
                "spark.shuffle.tpu.transportNumStripes": 2,
                "spark.shuffle.tpu.transportStripeThreshold": "64k",
            })
            node = Node(("127.0.0.1", node_port), conf)
            connect = TcpNetwork().connect
            try:
                ev0 = GLOBAL_REGISTRY.counter(
                    "transport_channel_evictions_total").value
                for i, peer in enumerate(fleet.addresses):
                    addr = (i * 7919) % (len(pattern) - 300_000)
                    read_one(node, connect,
                             peer, BlockLocation(addr, 300_000, 1))
                # reconnect an early (long-evicted) peer: transparent
                read_one(node, connect, fleet.addresses[0],
                         BlockLocation(5, 200_000, 1))
                with node._active_lock:
                    cached = len(node._active)
                assert cached <= cap, (engine, cached)
                assert GLOBAL_REGISTRY.counter(
                    "transport_channel_evictions_total").value > ev0
                # read groups don't accumulate per peer either: only
                # peers with live cached channels keep one
                assert len(node._read_groups) <= cap, (
                    engine, len(node._read_groups))
                # census ceilings: threads/fds bounded by conf, not by
                # n_peers × stripes.  Evicted channels' reader threads
                # (threaded engine) and fleet-side sockets drain
                # asynchronously — poll to the bound.
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    census = transport_census()
                    grown_threads = (census["transport_threads"]
                                     - before["transport_threads"])
                    grown_fds = census["open_fds"] - before["open_fds"]
                    # threaded: ≤ cap readers + serve + completion
                    # pool; async: 1 loop + pools
                    if (grown_threads <= cap + 8
                            and (before["open_fds"] < 0
                                 or grown_fds <= n_peers + 4 * cap + 32)):
                        break
                    time.sleep(0.1)
                assert grown_threads <= cap + 8, (
                    engine, before, census)
                if before["open_fds"] > 0 and census["open_fds"] > 0:
                    # n_peers listener fds belong to the fleet; the
                    # node's own sockets are bounded by the cache cap
                    # (requester + fleet-accepted end per channel)
                    assert grown_fds <= n_peers + 4 * cap + 32, (
                        engine, before, census)
                if engine == "on":
                    assert census["by_role"].get("disp", 0) == \
                        before["by_role"].get("disp", 0) + 1, census
            finally:
                node.stop()
                fleet.close()
    finally:
        GLOBAL_REGISTRY.enabled = prev_metrics


def test_delta_sync_republish_bytes_scale_with_change():
    """Delta-synced block locations: after the initial full publish, a
    republish following a few relocations ships O(changed) entry
    bytes, not O(partitions) — and the driver's table reflects the new
    locations despite segment reordering hazards (epoch guard)."""
    import time

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.rpc.messages import PublishMapTaskOutputMsg
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import LoopbackNetwork
    from sparkrdma_tpu.utils.types import BlockLocation

    num_parts = 4096
    changed = 5
    prev_metrics = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    net = LoopbackNetwork()
    conf_d = {"spark.shuffle.tpu.driverPort": 28900}
    driver = TpuShuffleManager(
        TpuShuffleConf(dict(conf_d)), is_driver=True, network=net,
        port=28900, stage_to_device=False,
    )
    ex = TpuShuffleManager(
        TpuShuffleConf(dict(conf_d)), is_driver=False, network=net,
        port=28910, executor_id="0", stage_to_device=False,
    )
    try:
        driver.register_shuffle(77, 1, HashPartitioner(num_parts))
        mto = MapTaskOutput(num_parts)
        for p in range(num_parts):
            mto.put(p, BlockLocation(p * 64, 64, 5))
        c_bytes = GLOBAL_REGISTRY.counter(
            "shuffle_publish_entry_bytes_total")
        b0 = c_bytes.value
        segs, entries, nbytes = ex.publish_map_output(77, 0, mto)
        assert entries == num_parts
        assert nbytes == num_parts * 16
        assert c_bytes.value - b0 == nbytes

        def driver_mto():
            with driver._outputs_lock:
                by_host = driver._outputs.get(77, {})
                for by_map in by_host.values():
                    if 0 in by_map:
                        return by_map[0]
            return None

        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            d_mto = driver_mto()
            if d_mto is not None and d_mto.is_complete:
                break
            time.sleep(0.02)
        assert d_mto is not None and d_mto.is_complete, (
            "full publish never completed on the driver")

        # relocate a few blocks and republish: the wire cost is the
        # changed entries, NOT another full table
        moved = [7, 8, 9, 1000, 4000][:changed]
        for p in moved:
            mto.put(p, BlockLocation(1 << 20 | p, 128, 6))
        b1 = c_bytes.value
        segs, entries, nbytes = ex.publish_map_output(77, 0, mto)
        assert entries == changed
        assert nbytes == changed * 16
        assert nbytes < num_parts * 16 // 100, (
            "republish bytes did not scale with changed locations")
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if d_mto.get_location(moved[-1]).length == 128:
                break
            time.sleep(0.02)
        for p in moved:
            assert d_mto.get_location(p) == BlockLocation(1 << 20 | p,
                                                          128, 6)
        # a straggling duplicate of the ORIGINAL full publish (epoch 0)
        # must not clobber the relocated entries on the driver
        orig = MapTaskOutput(num_parts)
        for p in range(num_parts):
            orig.put(p, BlockLocation(p * 64, 64, 5))
        stale = PublishMapTaskOutputMsg(
            ex.local_smid, 77, 0, num_parts, 0, num_parts - 1,
            orig.get_range_bytes(0, num_parts - 1), 0,
        )
        driver._handle_publish(stale)
        for p in moved:
            assert d_mto.get_location(p) == BlockLocation(1 << 20 | p,
                                                          128, 6)
    finally:
        ex.stop()
        driver.stop()
        GLOBAL_REGISTRY.enabled = prev_metrics


def test_async_dispatcher_bounded_threads_fds_at_high_peer_count():
    """Groundwork for the RDMAvisor-scale fabric (ROADMAP item 1): one
    node under transportAsyncDispatcher=on serves MANY simulated peers
    — raw sockets speaking the hello + OP_READ_REQ wire protocol — on
    ONE event-loop thread.  Transport thread count must stay a small
    constant (no per-connection readers, no accept thread) while fds
    scale only with the open sockets themselves."""
    import socket
    import time

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport import tcp as wire
    from sparkrdma_tpu.transport.channel import ChannelType
    from sparkrdma_tpu.transport.node import Node, transport_census

    import numpy as np

    n_peers = int(os.environ.get("SPARKRDMA_SCALE_PEERS", "96"))
    port = 27900
    pattern = (np.arange(1 << 20, dtype=np.uint32) % 251).astype(np.uint8)

    # drain reader threads left by earlier threaded-mode tests
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        before = transport_census()
        if before["by_role"].get("tcp", 0) == 0:
            break
        time.sleep(0.05)

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportAsyncDispatcher": "on",
        "spark.shuffle.tpu.transportServeThreads": 2,
    })
    net = TcpNetwork(listen_backlog=max(128, n_peers))
    node = Node(("127.0.0.1", port), conf)
    net.register(node)
    arena = ArenaManager()
    seg = arena.register(pattern, zero_copy_ok=True)
    node.register_block_store(seg.mkey, arena)

    type_idx = list(ChannelType).index(ChannelType.READ_REQUESTOR)
    socks = []
    try:
        for i in range(n_peers):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(wire._HELLO.pack(wire._MAGIC, type_idx,
                                       50000 + i, wire.WIRE_VERSION))
            assert s.recv(1) == b"\x01", f"handshake {i} rejected"
            s.settimeout(30)
            socks.append(s)

        # all peers post a read BEFORE any response is drained — the
        # loop multiplexes every socket concurrently
        blk = 4096
        for i, s in enumerate(socks):
            addr = (i * 7919) % (len(pattern) - blk)
            payload = wire._REQ_HDR.pack(1, 1) + wire._LOC.pack(
                addr, blk, seg.mkey
            )
            s.sendall(wire._HDR.pack(wire.OP_READ_REQ, len(payload))
                      + payload)

        def recv_exact(s, n):
            buf = b""
            while len(buf) < n:
                chunk = s.recv(n - len(buf))
                assert chunk, "peer socket closed early"
                buf += chunk
            return buf

        for i, s in enumerate(socks):
            opcode, length = wire._HDR.unpack(
                recv_exact(s, wire._HDR.size))
            assert opcode == wire.OP_READ_RESP
            body = recv_exact(s, length)
            req_id, status = wire._RESP_HDR.unpack_from(body, 0)
            assert status == 0, body[wire._RESP_HDR.size:]
            (n,) = wire._LEN.unpack_from(body, wire._RESP_HDR.size)
            assert n == blk
            addr = (i * 7919) % (len(pattern) - blk)
            got = body[wire._RESP_HDR.size + wire._LEN.size:]
            assert got == pattern[addr:addr + blk].tobytes(), \
                f"peer {i} payload corrupt"

        census = transport_census()
        # O(1) transport threads: 1 loop + ≤2 serve + ≤4 completion
        # pool — NOT O(n_peers); and zero thread-per-channel readers
        grown = (census["transport_threads"]
                 - before["transport_threads"])
        assert grown <= 8, (before, census)
        assert census["by_role"].get("tcp", 0) == \
            before["by_role"].get("tcp", 0), census
        assert census["by_role"].get("disp", 0) == \
            before["by_role"].get("disp", 0) + 1, census
        # fds scale only with the sockets themselves — BOTH ends of
        # every connection live in this one test process (client sock +
        # accepted sock), plus small slack for the listener, wake pipe
        # and selector
        if before["open_fds"] > 0 and census["open_fds"] > 0:
            assert census["open_fds"] - before["open_fds"] \
                <= 2 * n_peers + 16, (before, census)
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        node.stop()
        net.unregister(node)


def test_fabric_and_tier_budget_hold_ceilings_together():
    """ISSUE 8 acceptance: a node fetching striped blocks from a
    256-peer fabric through the bounded channel cache while ITS OWN
    tiered block store churns an out-of-core dataset through a tiny
    hot budget — fds and transport threads stay bounded by conf (cache
    cap / lane pool / O(1) dispatcher) AND the tier's resident hot
    bytes never exceed ``tierHotBytes``, together, under concurrent
    load."""
    import threading
    import time

    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.memory.mapped_file import MappedFile
    from sparkrdma_tpu.memory.tier import TieredBlockStore
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.transport.node import Node, transport_census
    from sparkrdma_tpu.transport.simfleet import SimPeerFleet
    from sparkrdma_tpu.utils.types import BlockLocation

    n_peers = int(os.environ.get("SPARKRDMA_FABRIC_PEERS", "256"))
    cap = 8
    block = 32 << 10
    n_blocks = 64
    budget = 8 * block
    pattern = (np.arange(2 << 20, dtype=np.uint32) % 251).astype(np.uint8)
    prev_metrics = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    before = transport_census()
    fleet = SimPeerFleet(n_peers, 28700, pattern)
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportMaxCachedChannels": cap,
        "spark.shuffle.tpu.transportLanePoolSize": 4,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        "spark.shuffle.tpu.transportServeThreads": 2,
    })
    node = Node(("127.0.0.1", 28690), conf)
    connect = TcpNetwork().connect
    # the node's own out-of-core dataset: 64 x 32 KiB blocks behind an
    # 8-block hot budget, readahead riding the node's serve pool
    tier = TieredBlockStore(
        hot_bytes=budget, prefetch_blocks=2,
        submitter=node.submit_serve,
    )
    arena = ArenaManager()
    rng = np.random.default_rng(11)
    tier_pat = rng.integers(0, 256, n_blocks * block, dtype=np.uint8)
    mf = MappedFile(tier_pat.tobytes(), direct_write=False,
                    defer_map=True)
    seg = tier.adopt(
        mf, [(i * block, block) for i in range(n_blocks)],
        n_blocks * block, 0, arena,
    )
    peak = [0]
    churn_errs = []
    stop_churn = threading.Event()

    def churn():
        order = list(range(n_blocks))
        rng2 = np.random.default_rng(13)
        try:
            while not stop_churn.is_set():
                rng2.shuffle(order)
                for i in order:
                    got = seg.read(i * block, block - 64)  # promoting
                    if not np.array_equal(
                        got, tier_pat[i * block : i * block + block - 64]
                    ):
                        raise AssertionError(f"tier block {i} corrupt")
                    peak[0] = max(peak[0], tier.stats()["hot_bytes"])
                    if stop_churn.is_set():
                        return
        except BaseException as e:  # noqa: BLE001 - surfaced below
            churn_errs.append(e)

    churner = threading.Thread(target=churn, daemon=True)
    churner.start()

    def read_one(peer, loc, timeout=60):
        done = threading.Event()
        res = {}
        node.get_read_group(peer, connect).read_blocks(
            [loc],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
        )
        assert done.wait(timeout), f"fetch from {peer} hung"
        assert "ok" in res, res.get("error")
        got = res["ok"][0]
        got = got if isinstance(got, np.ndarray) else np.frombuffer(
            memoryview(got), np.uint8)
        assert np.array_equal(
            got, pattern[loc.address:loc.address + loc.length]
        ), f"corrupt payload from {peer}"

    try:
        for i, peer in enumerate(fleet.addresses):
            addr = (i * 7919) % (len(pattern) - 300_000)
            read_one(peer, BlockLocation(addr, 300_000, 1))
        with node._active_lock:
            cached = len(node._active)
        assert cached <= cap, cached
        stop_churn.set()
        churner.join(timeout=30)
        assert not churner.is_alive(), "tier churn wedged"
        assert not churn_errs, churn_errs
        # the ceilings hold TOGETHER: bounded fabric AND bounded tier
        assert peak[0] <= budget, (peak[0], budget)
        assert tier.stats()["hot_bytes"] <= budget
        assert GLOBAL_REGISTRY.counter("tier_demotes_total").value > 0
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            census = transport_census()
            grown_threads = (census["transport_threads"]
                             - before["transport_threads"])
            grown_fds = census["open_fds"] - before["open_fds"]
            if (grown_threads <= cap + 8
                    and (before["open_fds"] < 0
                         or grown_fds <= n_peers + 4 * cap + 32)):
                break
            time.sleep(0.1)
        assert grown_threads <= cap + 8, (before, census)
        if before["open_fds"] > 0 and census["open_fds"] > 0:
            assert grown_fds <= n_peers + 4 * cap + 32, (before, census)
    finally:
        stop_churn.set()
        node.stop()
        fleet.close()
        arena.stop()
        GLOBAL_REGISTRY.enabled = prev_metrics
