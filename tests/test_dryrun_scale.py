"""Pod-shaped virtual-mesh scale for the full dryrun (VERDICT r4 item 3).

``dryrun_multichip`` exercises every data-plane program — terasort
narrow+wide, wordcount, ring/Ulysses attention, TileExchange rounds,
joins, aggregation, external sort, the windowed record plane, and the
bulk session — over an n-device mesh.  The driver runs it at 8; this
test runs it at 16 in a subprocess (fresh backend, so the forced
device count takes), covering the regime where the plan matrices (E²
lengths), window cutter, and tile rounds grow beyond the default mesh
(reference full-mesh warm-up analog, RdmaShuffleManager.scala:70-118).

Set ``SPARKRDMA_DRYRUN_DEVICES`` to override (e.g. 32 — verified green
2026-07-31, see MULTICHIP_SCALE.json; ~6 min on the 1-core builder, so
the in-suite default stays 16).
"""

import os
import subprocess
import sys


def test_dryrun_multichip_16_devices():
    n = int(os.environ.get("SPARKRDMA_DRYRUN_DEVICES", "16"))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n}); "
         f"print('DRYRUN{n} OK')"],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env=env, capture_output=True, text=True, timeout=1800,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"DRYRUN{n} OK" in proc.stdout, proc.stdout


def test_async_dispatcher_bounded_threads_fds_at_high_peer_count():
    """Groundwork for the RDMAvisor-scale fabric (ROADMAP item 1): one
    node under transportAsyncDispatcher=on serves MANY simulated peers
    — raw sockets speaking the hello + OP_READ_REQ wire protocol — on
    ONE event-loop thread.  Transport thread count must stay a small
    constant (no per-connection readers, no accept thread) while fds
    scale only with the open sockets themselves."""
    import socket
    import time

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport import tcp as wire
    from sparkrdma_tpu.transport.channel import ChannelType
    from sparkrdma_tpu.transport.node import Node, transport_census

    import numpy as np

    n_peers = int(os.environ.get("SPARKRDMA_SCALE_PEERS", "96"))
    port = 27900
    pattern = (np.arange(1 << 20, dtype=np.uint32) % 251).astype(np.uint8)

    # drain reader threads left by earlier threaded-mode tests
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        before = transport_census()
        if before["by_role"].get("tcp", 0) == 0:
            break
        time.sleep(0.05)

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportAsyncDispatcher": "on",
        "spark.shuffle.tpu.transportServeThreads": 2,
    })
    net = TcpNetwork(listen_backlog=max(128, n_peers))
    node = Node(("127.0.0.1", port), conf)
    net.register(node)
    arena = ArenaManager()
    seg = arena.register(pattern, zero_copy_ok=True)
    node.register_block_store(seg.mkey, arena)

    type_idx = list(ChannelType).index(ChannelType.READ_REQUESTOR)
    socks = []
    try:
        for i in range(n_peers):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(wire._HELLO.pack(wire._MAGIC, type_idx,
                                       50000 + i, 0))
            assert s.recv(1) == b"\x01", f"handshake {i} rejected"
            s.settimeout(30)
            socks.append(s)

        # all peers post a read BEFORE any response is drained — the
        # loop multiplexes every socket concurrently
        blk = 4096
        for i, s in enumerate(socks):
            addr = (i * 7919) % (len(pattern) - blk)
            payload = wire._REQ_HDR.pack(1, 1) + wire._LOC.pack(
                addr, blk, seg.mkey
            )
            s.sendall(wire._HDR.pack(wire.OP_READ_REQ, len(payload))
                      + payload)

        def recv_exact(s, n):
            buf = b""
            while len(buf) < n:
                chunk = s.recv(n - len(buf))
                assert chunk, "peer socket closed early"
                buf += chunk
            return buf

        for i, s in enumerate(socks):
            opcode, length = wire._HDR.unpack(
                recv_exact(s, wire._HDR.size))
            assert opcode == wire.OP_READ_RESP
            body = recv_exact(s, length)
            req_id, status = wire._RESP_HDR.unpack_from(body, 0)
            assert status == 0, body[wire._RESP_HDR.size:]
            (n,) = wire._LEN.unpack_from(body, wire._RESP_HDR.size)
            assert n == blk
            addr = (i * 7919) % (len(pattern) - blk)
            got = body[wire._RESP_HDR.size + wire._LEN.size:]
            assert got == pattern[addr:addr + blk].tobytes(), \
                f"peer {i} payload corrupt"

        census = transport_census()
        # O(1) transport threads: 1 loop + ≤2 serve + ≤4 completion
        # pool — NOT O(n_peers); and zero thread-per-channel readers
        grown = (census["transport_threads"]
                 - before["transport_threads"])
        assert grown <= 8, (before, census)
        assert census["by_role"].get("tcp", 0) == \
            before["by_role"].get("tcp", 0), census
        assert census["by_role"].get("disp", 0) == \
            before["by_role"].get("disp", 0) + 1, census
        # fds scale only with the sockets themselves — BOTH ends of
        # every connection live in this one test process (client sock +
        # accepted sock), plus small slack for the listener, wake pipe
        # and selector
        if before["open_fds"] > 0 and census["open_fds"] > 0:
            assert census["open_fds"] - before["open_fds"] \
                <= 2 * n_peers + 16, (before, census)
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        node.stop()
        net.unregister(node)
