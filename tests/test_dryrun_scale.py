"""Pod-shaped virtual-mesh scale for the full dryrun (VERDICT r4 item 3).

``dryrun_multichip`` exercises every data-plane program — terasort
narrow+wide, wordcount, ring/Ulysses attention, TileExchange rounds,
joins, aggregation, external sort, the windowed record plane, and the
bulk session — over an n-device mesh.  The driver runs it at 8; this
test runs it at 16 in a subprocess (fresh backend, so the forced
device count takes), covering the regime where the plan matrices (E²
lengths), window cutter, and tile rounds grow beyond the default mesh
(reference full-mesh warm-up analog, RdmaShuffleManager.scala:70-118).

Set ``SPARKRDMA_DRYRUN_DEVICES`` to override (e.g. 32 — verified green
2026-07-31, see MULTICHIP_SCALE.json; ~6 min on the 1-core builder, so
the in-suite default stays 16).
"""

import os
import subprocess
import sys


def test_dryrun_multichip_16_devices():
    n = int(os.environ.get("SPARKRDMA_DRYRUN_DEVICES", "16"))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n}); "
         f"print('DRYRUN{n} OK')"],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env=env, capture_output=True, text=True, timeout=1800,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"DRYRUN{n} OK" in proc.stdout, proc.stdout
