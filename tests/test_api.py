"""Job-level API: the local[*] driver experience (BASELINE config 1
groupByKey / reduceByKey / sortByKey jobs end-to-end)."""

from collections import defaultdict

import numpy as np
import pytest

from sparkrdma_tpu.api import TpuShuffleContext


@pytest.fixture(scope="module")
def ctx(devices):
    c = TpuShuffleContext(num_executors=3, base_port=43000,
                          stage_to_device=False)
    yield c
    c.stop()


def test_narrow_ops_fused(ctx):
    ds = ctx.parallelize(range(100), num_slices=5)
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 4 == 0).collect()
    assert sorted(out) == [x * 2 for x in range(100) if (x * 2) % 4 == 0]
    assert ds.flat_map(lambda x: [x, x]).count() == 200


def test_reduce_by_key(ctx):
    ds = ctx.parallelize(range(10_000), num_slices=8)
    got = dict(
        ds.map(lambda x: (x % 97, 1))
        .reduce_by_key(lambda a, b: a + b, num_partitions=5)
        .collect()
    )
    expected = defaultdict(int)
    for x in range(10_000):
        expected[x % 97] += 1
    assert got == dict(expected)


def test_group_by_key(ctx):
    ds = ctx.parallelize([(i % 7, i) for i in range(500)], num_slices=6)
    got = dict(ds.group_by_key(num_partitions=4).collect())
    expected = defaultdict(list)
    for i in range(500):
        expected[i % 7].append(i)
    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == expected[k]


def test_sort_by_key_global_order(ctx):
    import random

    rng = random.Random(3)
    keys = [rng.randrange(10**6) for _ in range(3000)]
    ds = ctx.parallelize([(k, k + 1) for k in keys], num_slices=6)
    out = ds.sort_by_key(num_partitions=5).collect()
    assert [k for k, _ in out] == sorted(keys)
    assert all(v == k + 1 for k, v in out)


def test_join(ctx):
    left = ctx.parallelize([(i % 10, f"L{i}") for i in range(50)], 4)
    right = ctx.parallelize([(i % 10, f"R{i}") for i in range(20)], 3)
    got = left.join(right, num_partitions=4).collect()
    expected = []
    for i in range(50):
        for j in range(20):
            if i % 10 == j % 10:
                expected.append((i % 10, (f"L{i}", f"R{j}")))
    assert sorted(got) == sorted(expected)


def test_device_workloads_via_context(ctx):
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 20, size=4096, dtype=np.int32)
    sk, _ = ctx.device_sort(keys, keys)
    assert (np.diff(sk) >= 0).all()
    counts = ctx.device_count((keys % 13).astype(np.int32))
    assert sum(counts.values()) == len(keys)


def test_device_aggregate_and_join_via_context(ctx, devices):
    rng = np.random.default_rng(21)
    keys = rng.integers(0, 40, 3000).astype(np.int32)
    vals = rng.integers(-50, 50, 3000).astype(np.int32)
    out = ctx.device_aggregate(keys, vals)
    for k in np.unique(keys):
        sel = vals[keys == k]
        assert out[int(k)].sum == int(sel.sum())
        assert out[int(k)].max == int(sel.max())

    dk = np.arange(100, dtype=np.int32)
    dv = dk * 2
    fk = rng.integers(0, 200, 500).astype(np.int32)
    fv = rng.integers(0, 9, 500).astype(np.int32)
    for broadcast in (False, True):
        jk, jfv, jdv = ctx.device_join(fk, fv, dk, dv, broadcast=broadcast)
        m = fk < 100
        assert len(jk) == m.sum()
        assert (jdv == jk * 2).all()


def test_dataset_cogroup_distinct_count_by_key(ctx):
    left = ctx.parallelize([(k % 5, k) for k in range(40)], num_slices=4)
    right = ctx.parallelize([(k % 7, -k) for k in range(21)], num_slices=3)
    cg = dict(left.cogroup(right, num_partitions=4).collect())
    lpairs = [(k2 % 5, k2) for k2 in range(40)]
    rpairs = [(k2 % 7, -k2) for k2 in range(21)]
    for k, (vs, ws) in cg.items():
        assert sorted(vs) == sorted(v for kk, v in lpairs if kk == k)
        assert sorted(ws) == sorted(w for kk, w in rpairs if kk == k)
    assert set(cg) == set(range(7))

    d = ctx.parallelize([1, 2, 2, 3, 3, 3, 4] * 3, num_slices=4)
    assert sorted(d.distinct(num_partitions=3).collect()) == [1, 2, 3, 4]

    kv = ctx.parallelize([("a", 1), ("b", 2), ("a", 3)] * 5, num_slices=2)
    assert kv.count_by_key() == {"a": 10, "b": 5}


def test_dataset_join_variants(ctx):
    left = ctx.parallelize([(1, "x"), (1, "y"), (2, "z"), (9, "q")],
                           num_slices=2)
    right = ctx.parallelize([(1, 10), (2, 20), (3, 30)], num_slices=2)
    inner = sorted(left.join(right, num_partitions=3).collect())
    assert inner == [(1, ("x", 10)), (1, ("y", 10)), (2, ("z", 20))]
    louter = sorted(left.join(right, how="left_outer").collect())
    assert louter == [
        (1, ("x", 10)), (1, ("y", 10)), (2, ("z", 20)), (9, ("q", None))
    ]
    semi = sorted(left.join(right, how="semi").collect())
    assert semi == [(1, "x"), (1, "y"), (2, "z")]
    anti = sorted(left.join(right, how="anti").collect())
    assert anti == [(9, "q")]
    router = sorted(
        left.join(right, how="right_outer").collect(),
        key=lambda kv: (kv[0], str(kv[1])),
    )
    assert router == [
        (1, ("x", 10)), (1, ("y", 10)), (2, ("z", 20)), (3, (None, 30))
    ]
    fouter = sorted(
        left.join(right, how="full_outer").collect(),
        key=lambda kv: (kv[0], str(kv[1])),
    )
    assert fouter == [
        (1, ("x", 10)), (1, ("y", 10)), (2, ("z", 20)),
        (3, (None, 30)), (9, ("q", None)),
    ]
    with pytest.raises(ValueError, match="how"):
        left.join(right, how="cross")


def test_dataset_aggregate_fold_subtract_by_key(ctx):
    kv = ctx.parallelize(
        [(k % 3, v) for k, v in enumerate(range(30))], num_slices=4
    )
    # aggregateByKey with an asymmetric MUTABLE zero: a mutating
    # seq_func detects any shared-zero regression (a shared list
    # would accumulate other keys' values)
    def seq(acc, v):
        acc.append(v)
        return acc

    agg = dict(
        kv.aggregate_by_key(
            [], seq, lambda a, b: a + b, num_partitions=3,
        ).collect()
    )
    for k in range(3):
        assert sorted(agg[k]) == [
            v for i, v in enumerate(range(30)) if i % 3 == k
        ]
    # the mutable zero must not be shared across keys
    assert sum(len(v) for v in agg.values()) == 30
    fold = dict(kv.fold_by_key(0, lambda a, b: a + b).collect())
    for k in range(3):
        assert fold[k] == sum(
            v for i, v in enumerate(range(30)) if i % 3 == k
        )
    other = ctx.parallelize([(0, "zz"), (7, "yy")], num_slices=2)
    sub = sorted(kv.subtract_by_key(other).collect())
    assert sub == sorted(
        (k % 3, v) for k, v in enumerate(range(30)) if k % 3 != 0
    )


def test_dataset_combine_by_key(ctx):
    kv = ctx.parallelize(
        [(k % 3, v) for k, v in enumerate(range(30))], num_slices=4
    )
    # combiner tracks (sum, count) -> mean per key
    out = dict(
        kv.combine_by_key(
            create_combiner=lambda v: (v, 1),
            merge_value=lambda c, v: (c[0] + v, c[1] + 1),
            merge_combiners=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            num_partitions=3,
        ).collect()
    )
    for k in range(3):
        vals = [v for i, v in enumerate(range(30)) if i % 3 == k]
        assert out[k] == (sum(vals), len(vals))


def test_dataset_staples(ctx):
    ds = ctx.parallelize([(k % 4, k) for k in range(40)], num_slices=4)
    assert sorted(ds.keys().collect()) == sorted(k % 4 for k in range(40))
    assert sorted(ds.values().collect()) == list(range(40))
    assert sorted(ds.map_values(lambda v: v * 2).collect()) == sorted(
        (k % 4, k * 2) for k in range(40)
    )
    u = ds.union(ctx.parallelize([(9, 99)], num_slices=1))
    assert len(u.collect()) == 41
    assert ds.first() in [(k % 4, k) for k in range(40)]
    assert len(ds.take(7)) == 7
    samp = ds.sample(0.5, seed=3).collect()
    assert 0 < len(samp) < 40
    assert set(samp) <= set((k % 4, k) for k in range(40))


def test_repartition_and_sort_within_partitions(ctx):
    import random as _random

    rng = _random.Random(5)
    data = [(rng.randrange(1000), i) for i in range(500)]
    out = ctx.parallelize(data, num_slices=4) \
        .repartition_and_sort_within_partitions(num_partitions=5)
    parts = out._materialize()
    assert len(parts) == 5
    seen = []
    for part in parts:
        ks = [k for k, _v in part]
        assert ks == sorted(ks), "partition not key-sorted"
        seen.extend(part)
    assert sorted(seen) == sorted(data)


def test_dataset_cache_materializes_once(ctx):
    calls = []

    def probe(x):
        calls.append(x)
        return x * 2

    ds = ctx.parallelize(list(range(20)), num_slices=2).map(probe)
    assert sorted(ds.collect()) == sorted(x * 2 for x in range(20))
    assert sorted(ds.collect()) == sorted(x * 2 for x in range(20))
    assert len(calls) == 40  # uncached: chain re-ran per action

    calls.clear()
    cached = ctx.parallelize(list(range(20)), num_slices=2) \
        .map(probe).cache()
    assert sorted(cached.collect()) == sorted(x * 2 for x in range(20))
    assert sorted(cached.collect()) == sorted(x * 2 for x in range(20))
    assert cached.count() == 20
    assert len(calls) == 20  # cached: chain ran once


def test_dataset_top_k_per_key(ctx):
    rng = __import__("random").Random(7)
    data = [(i % 5, rng.randrange(-100, 100)) for i in range(300)]
    got = dict(
        ctx.parallelize(data, num_slices=4)
        .top_k_per_key(3, num_partitions=4)
        .collect()
    )
    for kk in range(5):
        want = sorted((v for q, v in data if q == kk), reverse=True)[:3]
        assert list(got[kk]) == want
    with pytest.raises(ValueError, match="k must be positive"):
        ctx.parallelize(data, num_slices=2).top_k_per_key(0)


def test_device_top_k_and_join_how_via_context(ctx):
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 9, 2000).astype(np.int32)
    vals = rng.integers(-100, 100, 2000).astype(np.int32)
    top = ctx.device_top_k(keys, vals, 2)
    for kk in np.unique(keys):
        want = np.sort(vals[keys == kk])[::-1][:2].tolist()
        assert top[int(kk)] == want
    fk = np.array([1, 2, 9], np.int32)
    fv = np.array([10, 20, 90], np.int32)
    dk = np.array([1, 2], np.int32)
    dv = np.array([5, 6], np.int32)
    k_, v_ = ctx.device_join(fk, fv, dk, dv, how="anti")
    assert k_.tolist() == [9] and v_.tolist() == [90]
