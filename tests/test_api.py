"""Job-level API: the local[*] driver experience (BASELINE config 1
groupByKey / reduceByKey / sortByKey jobs end-to-end)."""

from collections import defaultdict

import numpy as np
import pytest

from sparkrdma_tpu.api import TpuShuffleContext


@pytest.fixture(scope="module")
def ctx(devices):
    c = TpuShuffleContext(num_executors=3, base_port=43000,
                          stage_to_device=False)
    yield c
    c.stop()


def test_narrow_ops_fused(ctx):
    ds = ctx.parallelize(range(100), num_slices=5)
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 4 == 0).collect()
    assert sorted(out) == [x * 2 for x in range(100) if (x * 2) % 4 == 0]
    assert ds.flat_map(lambda x: [x, x]).count() == 200


def test_reduce_by_key(ctx):
    ds = ctx.parallelize(range(10_000), num_slices=8)
    got = dict(
        ds.map(lambda x: (x % 97, 1))
        .reduce_by_key(lambda a, b: a + b, num_partitions=5)
        .collect()
    )
    expected = defaultdict(int)
    for x in range(10_000):
        expected[x % 97] += 1
    assert got == dict(expected)


def test_group_by_key(ctx):
    ds = ctx.parallelize([(i % 7, i) for i in range(500)], num_slices=6)
    got = dict(ds.group_by_key(num_partitions=4).collect())
    expected = defaultdict(list)
    for i in range(500):
        expected[i % 7].append(i)
    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == expected[k]


def test_sort_by_key_global_order(ctx):
    import random

    rng = random.Random(3)
    keys = [rng.randrange(10**6) for _ in range(3000)]
    ds = ctx.parallelize([(k, k + 1) for k in keys], num_slices=6)
    out = ds.sort_by_key(num_partitions=5).collect()
    assert [k for k, _ in out] == sorted(keys)
    assert all(v == k + 1 for k, v in out)


def test_join(ctx):
    left = ctx.parallelize([(i % 10, f"L{i}") for i in range(50)], 4)
    right = ctx.parallelize([(i % 10, f"R{i}") for i in range(20)], 3)
    got = left.join(right, num_partitions=4).collect()
    expected = []
    for i in range(50):
        for j in range(20):
            if i % 10 == j % 10:
                expected.append((i % 10, (f"L{i}", f"R{j}")))
    assert sorted(got) == sorted(expected)


def test_device_workloads_via_context(ctx):
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 20, size=4096, dtype=np.int32)
    sk, _ = ctx.device_sort(keys, keys)
    assert (np.diff(sk) >= 0).all()
    counts = ctx.device_count((keys % 13).astype(np.int32))
    assert sum(counts.values()) == len(keys)


def test_device_aggregate_and_join_via_context(ctx, devices):
    rng = np.random.default_rng(21)
    keys = rng.integers(0, 40, 3000).astype(np.int32)
    vals = rng.integers(-50, 50, 3000).astype(np.int32)
    out = ctx.device_aggregate(keys, vals)
    for k in np.unique(keys):
        sel = vals[keys == k]
        assert out[int(k)].sum == int(sel.sum())
        assert out[int(k)].max == int(sel.max())

    dk = np.arange(100, dtype=np.int32)
    dv = dk * 2
    fk = rng.integers(0, 200, 500).astype(np.int32)
    fv = rng.integers(0, 9, 500).astype(np.int32)
    for broadcast in (False, True):
        jk, jfv, jdv = ctx.device_join(fk, fv, dk, dv, broadcast=broadcast)
        m = fk < 100
        assert len(jk) == m.sum()
        assert (jdv == jk * 2).all()
