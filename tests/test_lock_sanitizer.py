"""Runtime lock sanitizer (utils/dbglock.py, conf lockDebug):

- with the conf OFF, the transport allocates plain ``threading``
  primitives (identity-checked — zero wrapper overhead on the default
  path);
- with it ON, a concurrent stress of the three threaded planes
  (striped remote reads, a bulk-exchange window barrier, metrics
  publishing) completes with ZERO rank violations and populates the
  ``lock_hold_us`` hold-time histograms;
- seeded inversions raise :class:`LockOrderViolation` (unit level)."""

import threading
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.utils.dbglock import (
    DebugLock,
    LockOrderViolation,
    dbg_condition,
    dbg_lock,
    get_lock_factory,
)

BASE_PORT = 39400

_PLAIN_LOCK_TYPE = type(threading.Lock())


@pytest.fixture()
def lock_factory():
    """Save/restore the process-global factory + registry state."""
    factory = get_lock_factory()
    prev = factory.enabled
    prev_reg = GLOBAL_REGISTRY.enabled
    yield factory
    factory.enabled = prev
    GLOBAL_REGISTRY.enabled = prev_reg
    GLOBAL_REGISTRY.reset()


# -- identity: disabled path is plain threading -------------------------------


def test_disabled_factory_allocates_plain_primitives(lock_factory):
    lock_factory.enabled = False
    assert type(dbg_lock("x", 1)) is _PLAIN_LOCK_TYPE
    assert type(dbg_condition("x", 1)) is threading.Condition
    node = Node(("127.0.0.1", BASE_PORT + 90), TpuShuffleConf())
    try:
        assert type(node._active_lock) is _PLAIN_LOCK_TYPE
        assert type(node._block_store_lock) is _PLAIN_LOCK_TYPE
    finally:
        node.stop()


def test_lock_debug_conf_wraps_transport_locks(lock_factory):
    lock_factory.enabled = False
    net = LoopbackNetwork()
    conf = TpuShuffleConf({"spark.shuffle.tpu.lockDebug": True})
    driver = TpuShuffleManager(
        conf, is_driver=True, network=net, port=BASE_PORT + 80,
    )
    try:
        assert isinstance(driver.node._active_lock, DebugLock)
        assert isinstance(driver._plan_lock, DebugLock)
        # conditions wrap a DebugLock inside a real Condition
        assert isinstance(driver._window_lock, DebugLock)
    finally:
        driver.stop()


# -- unit: violations raise ---------------------------------------------------


def test_rank_inversion_raises(lock_factory):
    lock_factory.enabled = True
    lo, hi = dbg_lock("t.lo", 10), dbg_lock("t.hi", 20)
    with lo:
        with hi:
            pass  # monotonic: fine
    with pytest.raises(LockOrderViolation):
        with hi:
            with lo:
                pass


def test_nonreentrant_reacquire_raises(lock_factory):
    lock_factory.enabled = True
    a = dbg_lock("t.a", 10)
    with pytest.raises(LockOrderViolation):
        with a:
            with a:
                pass
    # the failed acquire must not leak a held entry
    with a:
        pass


def test_condition_wait_keeps_rank_bookkeeping(lock_factory):
    lock_factory.enabled = True
    cv = dbg_condition("t.cv", 30)
    hits = []

    def consumer():
        with cv:
            while not hits:
                cv.wait(timeout=5)

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    with cv:
        hits.append(1)
        cv.notify_all()
    t.join(5)
    assert not t.is_alive()
    # after the wait/wake cycle the waiter's stack drained: acquiring a
    # LOWER rank now must be legal on this thread
    lower = dbg_lock("t.lower", 10)
    with lower:
        pass


# -- the concurrent stress ----------------------------------------------------


def _run_shuffle(driver, executors, shuffle_id, errors):
    """One full write→publish→resolve→striped-fetch→read cycle; block
    sizes exceed the stripe threshold so remote fetches ride the
    multi-lane scatter path."""
    try:
        num_maps, num_parts = 2, 4
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(shuffle_id, num_maps, part)
        payload = "v" * 2000
        records = [
            [(f"k{j % num_parts}", payload) for j in range(200)]
            for _m in range(num_maps)
        ]
        maps_by_host = defaultdict(list)
        for map_id, recs in enumerate(records):
            ex = executors[map_id % len(executors)]
            w = ex.get_writer(handle, map_id)
            w.write(recs)
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)
        reader = executors[0].get_reader(
            handle, 0, num_parts, dict(maps_by_host)
        )
        got = sum(len(v) for _k, v in reader.read())
        assert got == num_maps * 200 * len(payload), got
        driver.unregister_shuffle(shuffle_id)
    except BaseException as e:  # propagate to the main thread
        errors.append(e)


class _FakeExchange:
    """Stand-in collective for the BulkShuffleSession barrier: streams
    transpose in host memory (the barrier's condvar choreography — the
    thing under test — is identical)."""

    def exchange_bytes(self, streams, lengths=None, local_sources=None):
        E = len(streams)
        return [[streams[s][d] for s in range(E)] for d in range(E)]


def _run_bulk_windows(errors):
    """Two contributor threads per window round-trip the session's
    keyed barrier (rank-26 condvar traffic)."""
    from sparkrdma_tpu.shuffle.bulk import BulkShuffleSession

    try:
        session = BulkShuffleSession(_FakeExchange(), n_hosts=2,
                                     timeout_s=30.0)
        for window in range(6):
            results = {}

            def contribute(me, window=window):
                results[me] = session.run(
                    me, [b"a" * 64, b"b" * 64], [[64, 64], [64, 64]],
                    round_key=(99, window),
                )

            ts = [threading.Thread(target=contribute, args=(me,))
                  for me in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
            assert results[0] == results[1], results
    except BaseException as e:
        errors.append(e)


def _run_metrics_publish(driver, stop, errors):
    try:
        while not stop.is_set():
            snap = GLOBAL_REGISTRY.snapshot()
            assert "counters" in snap
            driver.shuffle_telemetry(0)
            time.sleep(0.002)
    except BaseException as e:
        errors.append(e)


def test_stress_striped_read_bulk_window_metrics(lock_factory):
    """The acceptance stress: striped reads + bulk window barriers +
    metrics publishing run concurrently under lockDebug, with zero
    runtime rank violations and populated hold-time instruments."""
    lock_factory.enabled = False
    GLOBAL_REGISTRY.reset()
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.lockDebug": True,
        "spark.shuffle.tpu.metrics": True,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "4k",
        "spark.shuffle.tpu.driverPort": BASE_PORT,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "20s",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=BASE_PORT + 10 + i * 10, executor_id=str(i),
        )
        for i in range(2)
    ]
    assert lock_factory.enabled  # the conf flipped it on
    errors: list = []
    stop = threading.Event()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == 2 for e in executors):
                break
            time.sleep(0.01)
        publisher = threading.Thread(
            target=_run_metrics_publish, args=(driver, stop, errors)
        )
        publisher.start()
        bulk = threading.Thread(target=_run_bulk_windows, args=(errors,))
        bulk.start()
        shufflers = [
            threading.Thread(
                target=_run_shuffle,
                args=(driver, executors, sid, errors),
            )
            for sid in range(2)
        ]
        for t in shufflers:
            t.start()
        for t in shufflers + [bulk]:
            t.join(60)
            assert not t.is_alive(), "stress thread hung"
    finally:
        stop.set()
        publisher.join(10)
        for m in executors + [driver]:
            m.stop()
    assert not errors, errors

    # zero runtime rank violations...
    viol = [
        inst for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "lock_rank_violations_total"
    ]
    assert all(v.value == 0 for v in viol), [v.value for v in viol]
    # ...and the hold-time instruments populated across the planes
    holds = {
        dict(inst.labels)["lock"]: inst.count
        for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "lock_hold_us" and inst.count > 0
    }
    assert holds, "no lock_hold_us samples recorded"
    for expected in ("node.active", "bulk.session", "reader.pending"):
        assert expected in holds, (expected, sorted(holds))


def test_condition_wait_under_nested_hold_keeps_depth(lock_factory):
    """A wait inside a REENTRANT (depth-2) condition hold must restore
    the stack at the same depth: exiting the inner `with` may not
    underflow the bookkeeping, and rank checks stay live while the cv
    is still held."""
    lock_factory.enabled = True
    cv = dbg_condition("t.deep_cv", 30)
    lower = dbg_lock("t.deep_lower", 10)
    done = []

    def poker():
        time.sleep(0.05)
        with cv:
            done.append(1)
            cv.notify_all()

    t = threading.Thread(target=poker)
    t.start()
    with cv:
        with cv:  # reentrant: depth 2
            while not done:
                cv.wait(timeout=5)
        # depth back to 1 here — the cv is STILL held, so acquiring a
        # lower rank must still be flagged
        with pytest.raises(LockOrderViolation):
            with lower:
                pass
    t.join(5)
    # fully released: the lower-rank acquire is legal again
    with lower:
        pass


def test_cross_thread_release_does_not_poison_owner(lock_factory):
    """A plain DebugLock released by ANOTHER thread (signal usage)
    must not leave a phantom hold on the acquirer's stack — its later
    lower-rank acquires stay legal."""
    lock_factory.enabled = True
    sig = dbg_lock("t.signal", 50)
    low = dbg_lock("t.low", 10)
    sig.acquire()

    t = threading.Thread(target=sig.release)
    t.start()
    t.join(5)
    # the stale entry purges on the next lock op; rank 10 < 50 would
    # raise if the phantom hold survived
    with low:
        pass
