"""utils/trace.py bounded-append behavior + FetchHistogram bucket-edge
sample placement (ISSUE 1 satellite coverage)."""

import json

from sparkrdma_tpu.stats import FetchHistogram
from sparkrdma_tpu.utils.trace import Tracer


def test_tracer_bounded_append_sets_dropped(tmp_path):
    tr = Tracer(enabled=True, max_events=5)
    for i in range(8):
        tr.instant(f"e{i}")
    assert len(tr.events) == 5
    assert tr.dropped == 3
    path = tmp_path / "trace.json"
    tr.dump(str(path))
    doc = json.loads(path.read_text())
    assert doc["metadata"]["dropped_events"] == 3
    assert len(doc["traceEvents"]) == 5
    assert [e["name"] for e in doc["traceEvents"]] == [
        f"e{i}" for i in range(5)
    ]


def test_tracer_bound_applies_to_every_event_kind(tmp_path):
    tr = Tracer(enabled=True, max_events=2)
    with tr.span("s0"):
        pass
    tr.counter("c0", value=1)
    with tr.span("s1"):  # third event: dropped, counted
        pass
    tr.instant("i0")     # fourth: dropped, counted
    assert len(tr.events) == 2
    assert tr.dropped == 2
    tr.dump(str(tmp_path / "t.json"))
    doc = json.loads((tmp_path / "t.json").read_text())
    assert doc["metadata"]["dropped_events"] == 2


def test_tracer_disabled_records_nothing():
    tr = Tracer(enabled=False, max_events=2)
    with tr.span("s"):
        pass
    tr.instant("i")
    tr.counter("c", value=3)
    assert tr.events == []
    assert tr.dropped == 0


def test_fetch_histogram_bucket_edges():
    """A sample exactly on a bucket edge lands in the UPPER bucket
    (the reference's ``latency // bucket_ms`` placement); the last
    bucket is open-ended."""
    fh = FetchHistogram(bucket_ms=300, num_buckets=5)
    fh.add_sample(0)         # [0-300)
    fh.add_sample(299.999)   # [0-300)
    fh.add_sample(300)       # edge -> [300-600)
    fh.add_sample(599.999)   # [300-600)
    fh.add_sample(600)       # edge -> [600-900)
    fh.add_sample(1200)      # edge of the open-ended last bucket
    fh.add_sample(10**9)     # far overflow -> last bucket
    assert fh.total == 7
    assert fh.to_string() == (
        "[0-300ms]: 2, [300-600ms]: 2, [600-900ms]: 1, "
        "[900-1200ms]: 0, [1200ms+]: 2"
    )


def test_fetch_histogram_single_bucket_ms():
    fh = FetchHistogram(bucket_ms=1, num_buckets=3)
    for v in (0.0, 0.5, 1.0, 1.5, 2.0, 99.0):
        fh.add_sample(v)
    # 0,0.5 -> [0-1); 1,1.5 -> [1-2); 2,99 -> [2ms+]
    assert fh.to_string() == "[0-1ms]: 2, [1-2ms]: 2, [2ms+]: 2"
