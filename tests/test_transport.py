"""Transport layer: loopback channels, flow control, failure semantics
(SURVEY.md §2 rows RdmaNode/RdmaChannel; §5 failure detection)."""

import threading
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.transport import (
    ChannelType,
    FnCompletionListener,
    LoopbackNetwork,
    Node,
    TransportError,
)
from sparkrdma_tpu.transport.channel import BytesBlockStore
from sparkrdma_tpu.utils.types import BlockLocation


@pytest.fixture()
def net():
    network = LoopbackNetwork()
    nodes = []

    def make_node(port, **kw):
        node = Node(("127.0.0.1", port), **kw)
        network.register(node)
        nodes.append(node)
        return node

    yield network, make_node
    for n in nodes:
        n.stop()


def wait_for(event, timeout=5.0):
    assert event.wait(timeout), "timed out"


def test_rpc_roundtrip(net):
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    got = []
    done = threading.Event()
    b.set_receive_listener(lambda ch, frame: (got.append(frame), done.set()))
    ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    sent = threading.Event()
    ch.send_rpc([b"hello-frame"], FnCompletionListener(lambda r: sent.set()))
    wait_for(sent)
    wait_for(done)
    assert got == [b"hello-frame"]


def test_rpc_reply_channel(net):
    """Responder can answer on the reverse channel (driver↔executor RPC)."""
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    reply_done = threading.Event()
    replies = []

    def b_listener(ch, frame):
        ch.reply_channel().send_rpc(
            [b"re:" + frame], FnCompletionListener()
        )

    b.set_receive_listener(b_listener)
    a.set_receive_listener(
        lambda ch, frame: (replies.append(frame), reply_done.set())
    )
    ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    ch.send_rpc([b"ping"], FnCompletionListener())
    wait_for(reply_done)
    assert replies == [b"re:ping"]


def test_one_sided_read(net):
    """read_blocks pulls from the peer's block store without any peer
    receive listener — the one-sided READ property."""
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    # note: b has NO receive listener at all
    payload = bytes(range(256)) * 16
    b.register_block_store(7, BytesBlockStore(payload))
    ch = a.get_channel(b.address, ChannelType.READ_REQUESTOR, network.connect)
    result, done = [], threading.Event()
    locs = [BlockLocation(0, 16, 7), BlockLocation(256, 32, 7),
            BlockLocation(4000, 8, 7)]
    ch.read_blocks(locs, FnCompletionListener(lambda r: (result.append(r), done.set())))
    wait_for(done)
    blocks = result[0]
    assert blocks == [payload[0:16], payload[256:288], payload[4000:4008]]


def test_read_unknown_mkey_fails(net):
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    ch = a.get_channel(b.address, ChannelType.READ_REQUESTOR, network.connect)
    errs, done = [], threading.Event()
    ch.read_blocks(
        [BlockLocation(0, 4, 99)],
        FnCompletionListener(on_failure=lambda e: (errs.append(e), done.set())),
    )
    wait_for(done)
    assert isinstance(errs[0], TransportError)


def test_connect_refused_and_retries(net):
    network, make_node = net
    a = make_node(9000, conf=TpuShuffleConf(
        {"spark.shuffle.tpu.maxConnectionAttempts": 2}))
    with pytest.raises(TransportError, match="could not connect"):
        a.get_channel(("127.0.0.1", 9999), ChannelType.RPC_REQUESTOR, network.connect)


def test_channel_cache_reuse(net):
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    c1 = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    c2 = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    assert c1 is c2
    c3 = a.get_channel(b.address, ChannelType.READ_REQUESTOR, network.connect)
    assert c3 is not c1  # separate channel per traffic class


def test_partition_fails_inflight_and_reconnect_after_heal(net):
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    b.register_block_store(1, BytesBlockStore(b"x" * 64))
    ch = a.get_channel(b.address, ChannelType.READ_REQUESTOR, network.connect)
    network.partition(b.address)
    errs, done = [], threading.Event()
    ch.read_blocks(
        [BlockLocation(0, 4, 1)],
        FnCompletionListener(on_failure=lambda e: (errs.append(e), done.set())),
    )
    wait_for(done)
    assert isinstance(errs[0], TransportError)
    # channel went sticky-ERROR; cache must replace it after heal
    network.heal(b.address)
    ch2 = a.get_channel(b.address, ChannelType.READ_REQUESTOR, network.connect)
    assert ch2 is not ch
    ok, done2 = [], threading.Event()
    ch2.read_blocks(
        [BlockLocation(0, 4, 1)],
        FnCompletionListener(lambda r: (ok.append(r), done2.set())),
    )
    wait_for(done2)
    assert ok[0] == [b"xxxx"]


def test_stop_fails_outstanding_listeners(net):
    network, make_node = net
    a = make_node(9000)
    b = make_node(9001)
    ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    errs = []
    # stop the channel; queued-but-never-posted ops must fail too
    ch.stop()
    done = threading.Event()
    with pytest.raises(TransportError):
        ch.send_rpc([b"x"], FnCompletionListener(on_failure=lambda e: done.set()))


def test_send_budget_queues_instead_of_dropping(net):
    """More posts than queue depth: all must eventually complete (the
    pending-deque drain, reference RdmaChannel.java:379-439)."""
    network, make_node = net
    conf = TpuShuffleConf({"spark.shuffle.tpu.sendQueueDepth": 256})
    a = make_node(9000, conf=conf)
    b = make_node(9001)
    n_msgs = 1000  # > depth 256
    seen = []
    all_seen = threading.Event()

    def listener(ch, frame):
        seen.append(frame)
        if len(seen) == n_msgs:
            all_seen.set()

    b.set_receive_listener(listener)
    ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    completed = []
    all_done = threading.Event()

    def ok(_):
        completed.append(1)
        if len(completed) == n_msgs:
            all_done.set()

    for i in range(n_msgs):
        ch.send_rpc([b"m%d" % i], FnCompletionListener(ok))
    wait_for(all_done, 10)
    wait_for(all_seen, 10)
    assert len(seen) == n_msgs


def test_node_stop_parallel_teardown(net):
    network, make_node = net
    a = make_node(9000)
    peers = [make_node(9001 + i) for i in range(5)]
    chans = [
        a.get_channel(p.address, ChannelType.RPC_REQUESTOR, network.connect)
        for p in peers
    ]
    a.stop()
    assert all(not c.is_connected() for c in chans)


def test_credit_flow_control_blocks_then_drains(net):
    """swFlowControl: more frames than recv credits must stall, then flow
    once the receiver consumes and reports credits back."""
    network, make_node = net
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.recvQueueDepth": 256,  # min clamp
        "spark.shuffle.tpu.swFlowControl": True,
    })
    a = make_node(9000, conf=conf)
    b = make_node(9001, conf=conf)
    n_msgs = 1000  # 4x the credit budget
    seen = []
    all_seen = threading.Event()

    def listener(ch, frame):
        seen.append(frame)
        if len(seen) == n_msgs:
            all_seen.set()

    b.set_receive_listener(listener)
    ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, network.connect)
    for i in range(n_msgs):
        ch.send_rpc([b"c%d" % i], FnCompletionListener())
    wait_for(all_seen, 15)
    # every frame arrived exactly once despite credit stalls (ordering is
    # NOT guaranteed — the protocol's segments carry explicit ranges)
    assert sorted(seen) == sorted(b"c%d" % i for i in range(n_msgs))


def test_trace_spans_collected():
    from sparkrdma_tpu.utils.trace import Tracer

    t = Tracer(enabled=True)
    with t.span("outer", tag="x"):
        t.instant("marker")
    t.counter("bytes", value=42)
    names = [e["name"] for e in t.events]
    assert names == ["marker", "outer", "bytes"]
    import json as _json
    import tempfile, os
    path = os.path.join(tempfile.mkdtemp(), "trace.json")
    t.dump(path)
    with open(path) as f:
        doc = _json.load(f)
    assert len(doc["traceEvents"]) == 3
    # disabled tracer is a no-op
    t2 = Tracer(enabled=False)
    with t2.span("nope"):
        pass
    assert t2.events == []


def test_node_teardown_bounded_by_hung_channel(devices):
    """A channel whose stop() hangs must not wedge node teardown
    (teardownListenTimeout bounds the parallel-stop wait,
    reference RdmaNode.java:367-394)."""
    import threading
    import time as _time

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.transport.node import Node

    conf = TpuShuffleConf({"spark.shuffle.tpu.teardownListenTimeout": "100ms"})
    node = Node(("127.0.0.1", 45990), conf)

    class HungChannel:
        def __init__(self):
            self.ev = threading.Event()

        def stop(self):
            self.ev.wait(30)  # would block teardown for 30s

    hung = HungChannel()
    with node._passive_lock:
        node._passive.append(hung)
    t0 = _time.monotonic()
    node.stop()
    took = _time.monotonic() - t0
    hung.ev.set()  # release the worker thread
    assert took < 5, f"teardown blocked {took:.1f}s on a hung channel"
