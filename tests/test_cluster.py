"""Multi-process cluster harness (transport/simfleet.ProcessCluster):
cross-process shuffles over real sockets — bit-exactness against a
parent-side recomputation, fleet census/obs collection, and the first
executor-crash-mid-stage run across real process boundaries (clean
FetchFailed on the reader, surviving fleet stays healthy)."""

import pytest

from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport.simfleet import (
    ExecutorCommandError,
    _gen_records,
    records_digest,
)

pytestmark = pytest.mark.cluster

NUM_PARTS = 4
SHUFFLE = 7


def _expected_partitions(gen, num_maps, num_parts):
    """Parent-side recomputation of what every reducer must see: the
    generators are deterministic and stable_hash is cross-process
    stable, so the cluster's digests must match these bit-for-bit."""
    part = HashPartitioner(num_parts)
    by_part = {p: [] for p in range(num_parts)}
    for map_id in range(num_maps):
        for k, v in _gen_records(gen, map_id):
            by_part[part.partition(k)].append((k, v))
    return by_part


def _write_all(cluster, shuffle_id, num_maps, gen):
    for map_id in range(num_maps):
        cluster.call(map_id % cluster.n_executors, "write",
                     shuffle_id=shuffle_id, map_id=map_id, gen=gen)
    cluster.wait_published(shuffle_id, num_maps)


def test_cross_process_shuffle_bit_exact(cluster):
    """terasort-shaped records written in two executor processes read
    back with digests identical to the parent's local recomputation."""
    gen = {"kind": "terasort", "records": 300, "value_len": 32}
    cluster.register(SHUFFLE, num_maps=2, partitioner=("hash", NUM_PARTS))
    _write_all(cluster, SHUFFLE, 2, gen)
    expected = _expected_partitions(gen, 2, NUM_PARTS)
    total = 0
    for p in range(NUM_PARTS):
        out = cluster.read(p % 2, SHUFFLE, p, p + 1, digest=True)
        want = records_digest(expected[p])
        assert out["digest"] == want, f"partition {p} diverged"
        total += out["records"]
    assert total == 2 * 300


def test_cross_process_wordcount_aggregated(cluster):
    """sum-aggregated wordcount across processes: reduced counts equal
    the parent-side tally (map-side combine exercises the aggregator
    rebuilt from its declarative kind inside each child)."""
    gen = {"kind": "wordcount", "records": 400, "vocab": 23}
    cluster.register(SHUFFLE + 1, num_maps=2,
                     partitioner=("hash", NUM_PARTS), aggregator="sum",
                     map_side_combine=True)
    _write_all(cluster, SHUFFLE + 1, 2, gen)
    tally = {}
    for map_id in range(2):
        for k, v in _gen_records(gen, map_id):
            tally[k] = tally.get(k, 0) + v
    part = HashPartitioner(NUM_PARTS)
    got = {}
    for p in range(NUM_PARTS):
        out = cluster.read(p % 2, SHUFFLE + 1, p, p + 1)
        for k, v in out["data"]:
            assert part.partition(k) == p
            assert k not in got, f"key {k} emitted twice"
            got[k] = v
    assert got == tally


def test_fleet_census_and_obs_collection(cluster):
    """census() reports every live process; stop() leaves per-process
    flight-recorder dumps the collect() merge path folds into one
    trace document."""
    census = cluster.census()
    assert sorted(census["executors"]) == [0, 1]
    for info in census["executors"].values():
        c = info["census"]
        assert c["pid"] != census["driver"]["pid"]
        assert c["fds"] > 0 and c["threads"] >= 1
        assert c["cpu_user_s"] >= 0.0
    cluster.stop()
    merged = cluster.collect()
    # driver + 2 executors each dump at manager.stop()
    assert len(merged["dump_paths"]) >= 3
    assert len(merged["processes"]) == len(merged["dump_paths"])
    assert len(merged["log_paths"]) == 2


def test_executor_crash_mid_stage(cluster):
    """SIGKILL one executor after publish: a reader needing its blocks
    gets a clean FetchFailed (through the PR-15 retry/breaker plane,
    now across a real process boundary) and the surviving executor
    keeps serving fresh shuffles."""
    gen = {"kind": "terasort", "records": 120, "value_len": 16}
    cluster.register(SHUFFLE + 2, num_maps=2,
                     partitioner=("hash", NUM_PARTS))
    _write_all(cluster, SHUFFLE + 2, 2, gen)

    cluster.kill(1)
    assert not cluster.executors[1].alive

    # every partition spans both maps, so executor 0's read must cross
    # the dead peer — the failure must be FetchFailed, not a hang/pipe
    # error, and must come back through the command protocol
    with pytest.raises(ExecutorCommandError) as exc:
        cluster.read(0, SHUFFLE + 2, 0, 1, timeout=120.0)
    assert exc.value.kind == "FetchFailedError"

    # surviving fleet stays healthy: a new single-map shuffle written
    # and read wholly on executor 0 completes bit-exactly
    cluster.call(0, "register", shuffle_id=SHUFFLE + 3, num_maps=1,
                 partitioner=("hash", 2))
    cluster.call(0, "write", shuffle_id=SHUFFLE + 3, map_id=0, gen=gen)
    cluster.wait_published(SHUFFLE + 3, 1)
    expected = _expected_partitions(gen, 1, 2)
    for p in range(2):
        out = cluster.read(0, SHUFFLE + 3, p, p + 1, digest=True)
        assert out["digest"] == records_digest(expected[p])
