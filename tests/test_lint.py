"""Tier-1 wrapper for the style gate (tools/lint.py) + unit coverage
for the PY08 rule (no ``time.perf_counter()`` in library code outside
metrics/ and utils/trace.py — metric timing flows through the
registry)."""

import importlib.util
import pathlib

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_lint", REPO / "tools" / "lint.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_is_lint_clean():
    lint = _load_lint()
    findings = []
    for f in lint.py_files():
        lint.lint_python(f, findings)
    for f in lint.cc_files():
        lint.lint_cpp(f, findings)
    assert not findings, "\n".join(
        f"{rel}:{line}: {code} {msg}" for rel, line, code, msg in findings
    )


def test_py08_flags_perf_counter_in_library_code(tmp_path):
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "metrics").mkdir(parents=True)
    (lib / "utils").mkdir()

    bad_attr = lib / "hot.py"
    bad_attr.write_text("import time\nT0 = time.perf_counter()\n")
    bad_name = lib / "hot2.py"
    bad_name.write_text(
        "from time import perf_counter\nT0 = perf_counter()\n"
    )
    ok_metrics = lib / "metrics" / "registry.py"
    ok_metrics.write_text("import time\nT0 = time.perf_counter()\n")
    ok_trace = lib / "utils" / "trace.py"
    ok_trace.write_text("import time\nT0 = time.perf_counter()\n")

    findings = []
    for f in (bad_attr, bad_name, ok_metrics, ok_trace):
        lint.lint_python(f, findings, root=tmp_path)
    py08 = [str(rel) for rel, _l, code, _m in findings if code == "PY08"]
    assert sorted(py08) == [
        "sparkrdma_tpu/hot.py", "sparkrdma_tpu/hot2.py",
    ], findings
    # nothing else should fire on these files
    assert all(code == "PY08" for _r, _l, code, _m in findings), findings


def test_py08_ignores_non_library_code(tmp_path):
    lint = _load_lint()
    (tmp_path / "benchmarks").mkdir()
    bench = tmp_path / "benchmarks" / "b.py"
    bench.write_text("import time\nT0 = time.perf_counter()\n")
    findings = []
    lint.lint_python(bench, findings, root=tmp_path)
    assert not [f for f in findings if f[2] == "PY08"], findings


def test_py09_flags_hot_path_materialization(tmp_path):
    """.tobytes() / b"".join in the exchange hot paths regress the
    zero-copy data path; PY09 pins them out (noqa escapes)."""
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "parallel").mkdir(parents=True)
    (lib / "shuffle").mkdir()

    hot = lib / "parallel" / "exchange.py"
    hot.write_text(
        "def f(a, parts):\n"
        "    x = a.tobytes()\n"
        '    y = b"".join(parts)\n'
        "    z = a.tobytes()  # noqa\n"
        "    return x, y, z\n"
    )
    hot2 = lib / "shuffle" / "bulk.py"
    hot2.write_text("def g(a):\n    return a.tobytes()\n")
    cold = lib / "shuffle" / "writer.py"
    cold.write_text(
        'def h(a, parts):\n    return a.tobytes(), b"".join(parts)\n'
    )

    findings = []
    for f in (hot, hot2, cold):
        lint.lint_python(f, findings, root=tmp_path)
    py09 = sorted(
        (str(rel), line) for rel, line, code, _m in findings
        if code == "PY09"
    )
    assert py09 == [
        ("sparkrdma_tpu/parallel/exchange.py", 2),
        ("sparkrdma_tpu/parallel/exchange.py", 3),
        ("sparkrdma_tpu/shuffle/bulk.py", 2),
    ], findings


def test_py10_flags_tcp_hot_path_concat(tmp_path):
    """sendall(a + b)-style payload concatenation and per-frame bytes()
    materialization regress the scatter-gather TCP data path; PY10 pins
    them out of transport/tcp.py (noqa escapes)."""
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "transport").mkdir(parents=True)

    hot = lib / "transport" / "tcp.py"
    hot.write_text(
        "class C:\n"
        "    def _send_msg(self, opcode, payload):\n"
        "        self._sock.sendall(HDR.pack(opcode) + payload)\n"
        '        self._sock.sendall(b"".join(parts))\n'
        "    def _serve_read(self, payload):\n"
        "        body = bytes(payload)\n"
        "        deliberate = bytes(payload)  # noqa\n"
        "    def _post_read(self, locations, listener):\n"
        "        cold = bytes(locations)\n"
        "        self._sock.sendall(cold)\n"
    )
    cold = lib / "transport" / "loopback.py"
    cold.write_text(
        "def f(sock, a, b):\n"
        "    sock.sendall(a + b)\n"
        "    return bytes(a)\n"
    )

    findings = []
    for f in (hot, cold):
        lint.lint_python(f, findings, root=tmp_path)
    py10 = sorted(
        (str(rel), line) for rel, line, code, _m in findings
        if code == "PY10"
    )
    # line 3: sendall concat; line 4: sendall join; line 6: bytes() in
    # a hot function.  NOT flagged: the noqa'd bytes() (7), bytes()/
    # sendall of a plain name in a non-hot function (9-10), and
    # anything outside transport/tcp.py.
    assert py10 == [
        ("sparkrdma_tpu/transport/tcp.py", 3),
        ("sparkrdma_tpu/transport/tcp.py", 4),
        ("sparkrdma_tpu/transport/tcp.py", 6),
    ], findings


def test_py13_flags_device_hot_path_host_copies(tmp_path):
    """np.asarray() / jax.device_get() / .tobytes() inside the
    device-exchange hot functions pull the padded payload back to
    host; PY13 pins them out (same-line noqa escapes for
    plan-metadata reads)."""
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "parallel").mkdir(parents=True)
    (lib / "memory").mkdir()

    hot = lib / "parallel" / "exchange.py"
    hot.write_text(
        "class TileExchange:\n"
        "    def exchange_padded(self, lengths, src_rows):\n"
        "        meta = np.asarray(lengths)  # noqa: PY13\n"
        "        mat = np.asarray(src_rows)\n"
        "        host = jax.device_get(src_rows)\n"
        "    def exchange_meta(self, lengths):\n"
        "        return np.asarray(lengths)\n"
    )
    hot2 = lib / "memory" / "device_arena.py"
    hot2.write_text(
        "def to_device(rows):\n"
        "    return rows.tobytes()\n"
        "def describe(rows):\n"
        "    return rows.tobytes()\n"
    )

    findings = []
    for f in (hot, hot2):
        lint.lint_python(f, findings, root=tmp_path)
    py13 = sorted(
        (str(rel), line) for rel, line, code, _m in findings
        if code == "PY13"
    )
    # Flagged: the bare np.asarray (4) and jax.device_get (5) inside
    # exchange_padded, and .tobytes() inside to_device (2).  NOT
    # flagged: the noqa'd metadata read (3), or the same calls in
    # functions outside DEVICE_HOT_FUNCS (exchange_meta, describe).
    assert py13 == [
        ("sparkrdma_tpu/memory/device_arena.py", 2),
        ("sparkrdma_tpu/parallel/exchange.py", 4),
        ("sparkrdma_tpu/parallel/exchange.py", 5),
    ], findings


def test_noqa_is_code_scoped(tmp_path):
    """# noqa: PYxx suppresses only PYxx; a scoped escape for one rule
    can no longer blanket-silence an unrelated hot-path rule."""
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "transport").mkdir(parents=True)
    hot = lib / "transport" / "tcp.py"
    hot.write_text(
        "class C:\n"
        "    def _send_msg(self, a, b):\n"
        "        self._sock.sendall(a + b)  # noqa: PY05\n"
        "        self._sock.sendall(a + b)  # noqa: PY10\n"
        "        self._sock.sendall(a + b)  # noqa\n"
        "        self._sock.sendall(a + b)  # noqa: PY02, PY10\n"
    )
    findings = []
    lint.lint_python(hot, findings, root=tmp_path)
    py10 = [line for _r, line, code, _m in findings if code == "PY10"]
    # only line 3 survives: its escape names an unrelated code
    assert py10 == [3], findings


def test_py05_noqa_on_multiline_from_import(tmp_path):
    """The escape is honored on the imported name's OWN line inside a
    multi-line from-import, and on the statement's first line."""
    lint = _load_lint()
    (tmp_path / "tools").mkdir()
    f = tmp_path / "tools" / "a.py"
    f.write_text(
        "from os import (\n"
        "    getcwd,\n"
        "    sep,  # noqa: PY05\n"
        ")\n"
        "from sys import (  # noqa: PY05\n"
        "    argv,\n"
        "    path,\n"
        ")\n"
    )
    findings = []
    lint.lint_python(f, findings, root=tmp_path)
    py05 = [(line, msg) for _r, line, code, msg in findings
            if code == "PY05"]
    # getcwd (line 2) flags at its own line; sep escaped on its line;
    # argv/path escaped by the statement-line noqa
    assert py05 == [(2, "unused import: getcwd")], findings


def test_py05_f401_alias_and_ast_usage(tmp_path):
    """F401 (the flake8 code) suppresses PY05; string annotations and
    __all__ exports count as real uses."""
    lint = _load_lint()
    (tmp_path / "tools").mkdir()
    f = tmp_path / "tools" / "b.py"
    f.write_text(
        "import json  # noqa: F401\n"
        "import os\n"
        "import struct\n"
        "import sys\n"
        "__all__ = [\"os\"]\n"
        "def g(x: \"struct.Struct\") -> None:\n"
        "    return None\n"
    )
    findings = []
    lint.lint_python(f, findings, root=tmp_path)
    py05 = [msg for _r, _l, code, msg in findings if code == "PY05"]
    # json: F401-aliased escape; os: __all__ export; struct: string
    # annotation; sys: genuinely unused
    assert py05 == ["unused import: sys"], findings


def test_noqa_code_followed_by_justification_prose(tmp_path):
    """The documented escape style '# noqa: CK02 <why>' scopes to the
    leading code token(s); the prose does not widen or break it."""
    lint = _load_lint()
    assert lint._noqa_codes("x()  # noqa: PY10 frame serialization") \
        == {"PY10"}
    assert lint._noqa_codes("x()  # noqa: CK02, CK03 deliberate") \
        == {"CK02", "CK03"}
    assert lint._noqa_codes("x()  # noqa") == set()
    assert lint._noqa_codes("x()") is None
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "transport").mkdir(parents=True)
    hot = lib / "transport" / "tcp.py"
    hot.write_text(
        "class C:\n"
        "    def _send_msg(self, a, b):\n"
        "        self._sock.sendall(a + b)  # noqa: PY10 serialized\n"
        "        self._sock.sendall(a + b)  # noqa: PY05 wrong code\n"
    )
    findings = []
    lint.lint_python(hot, findings, root=tmp_path)
    py10 = [line for _r, line, code, _m in findings if code == "PY10"]
    assert py10 == [4], findings


def _py11_root(tmp_path, readme: str):
    """A fake repo root: conf.py declaring two keys (one via a legacy
    rdma rename), one library file referencing keys, and a README."""
    lib = tmp_path / "sparkrdma_tpu"
    lib.mkdir()
    (lib / "conf.py").write_text(
        'LEGACY_RENAMES = {"useOdp": "lazyStaging"}\n\n\n'
        "class Conf:\n"
        "    def a(self):\n"
        '        self.get("tierHotBytes")\n'
        '        return self._bool("lazyStaging", False)\n'
    )
    (tmp_path / "README.md").write_text(readme)
    return lib


def test_py11_flags_undeclared_key_reference(tmp_path):
    lint = _load_lint()
    lib = _py11_root(tmp_path, "`tierHotBytes` and `lazyStaging`\n")
    (lib / "mod.py").write_text(
        '"""Knobs: spark.shuffle.tpu.tierHotBytes is declared,\n'
        "spark.shuffle.rdma.useOdp renames onto a declared key, but\n"
        'spark.shuffle.tpu.ghostKnob is drift."""\n'
    )
    findings = []
    lint.lint_conf_keys(findings, root=tmp_path)
    assert [(str(r), line, code) for r, line, code, _m in findings] == [
        ("sparkrdma_tpu/mod.py", 3, "PY11")
    ], findings
    assert "ghostKnob" in findings[0][3]


def test_py11_noqa_suppresses_reference_finding(tmp_path):
    lint = _load_lint()
    lib = _py11_root(tmp_path, "`tierHotBytes` and `lazyStaging`\n")
    (lib / "mod.py").write_text(
        "# spark.shuffle.tpu.ghostKnob  # noqa: PY11 - doc of a removed key\n"
    )
    findings = []
    lint.lint_conf_keys(findings, root=tmp_path)
    assert findings == []


def test_py11_flags_undocumented_declared_key(tmp_path):
    lint = _load_lint()
    # README documents tierHotBytes only: lazyStaging goes undocumented
    _py11_root(tmp_path, "| `tierHotBytes` | 64m |\n")
    findings = []
    lint.lint_conf_keys(findings, root=tmp_path)
    assert len(findings) == 1, findings
    rel, _line, code, msg = findings[0]
    assert code == "PY11" and "lazyStaging" in msg
    assert str(rel) == "README.md"


def test_py11_full_dotted_key_documents_too(tmp_path):
    lint = _load_lint()
    _py11_root(
        tmp_path,
        "spark.shuffle.tpu.tierHotBytes and spark.shuffle.tpu.lazyStaging\n",
    )
    findings = []
    lint.lint_conf_keys(findings, root=tmp_path)
    assert findings == []
