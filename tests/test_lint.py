"""Tier-1 wrapper for the style gate (tools/lint.py) + unit coverage
for the PY08 rule (no ``time.perf_counter()`` in library code outside
metrics/ and utils/trace.py — metric timing flows through the
registry)."""

import importlib.util
import pathlib

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_lint", REPO / "tools" / "lint.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_is_lint_clean():
    lint = _load_lint()
    findings = []
    for f in lint.py_files():
        lint.lint_python(f, findings)
    for f in lint.cc_files():
        lint.lint_cpp(f, findings)
    assert not findings, "\n".join(
        f"{rel}:{line}: {code} {msg}" for rel, line, code, msg in findings
    )


def test_py08_flags_perf_counter_in_library_code(tmp_path):
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "metrics").mkdir(parents=True)
    (lib / "utils").mkdir()

    bad_attr = lib / "hot.py"
    bad_attr.write_text("import time\nT0 = time.perf_counter()\n")
    bad_name = lib / "hot2.py"
    bad_name.write_text(
        "from time import perf_counter\nT0 = perf_counter()\n"
    )
    ok_metrics = lib / "metrics" / "registry.py"
    ok_metrics.write_text("import time\nT0 = time.perf_counter()\n")
    ok_trace = lib / "utils" / "trace.py"
    ok_trace.write_text("import time\nT0 = time.perf_counter()\n")

    findings = []
    for f in (bad_attr, bad_name, ok_metrics, ok_trace):
        lint.lint_python(f, findings, root=tmp_path)
    py08 = [str(rel) for rel, _l, code, _m in findings if code == "PY08"]
    assert sorted(py08) == [
        "sparkrdma_tpu/hot.py", "sparkrdma_tpu/hot2.py",
    ], findings
    # nothing else should fire on these files
    assert all(code == "PY08" for _r, _l, code, _m in findings), findings


def test_py08_ignores_non_library_code(tmp_path):
    lint = _load_lint()
    (tmp_path / "benchmarks").mkdir()
    bench = tmp_path / "benchmarks" / "b.py"
    bench.write_text("import time\nT0 = time.perf_counter()\n")
    findings = []
    lint.lint_python(bench, findings, root=tmp_path)
    assert not [f for f in findings if f[2] == "PY08"], findings


def test_py09_flags_hot_path_materialization(tmp_path):
    """.tobytes() / b"".join in the exchange hot paths regress the
    zero-copy data path; PY09 pins them out (noqa escapes)."""
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "parallel").mkdir(parents=True)
    (lib / "shuffle").mkdir()

    hot = lib / "parallel" / "exchange.py"
    hot.write_text(
        "def f(a, parts):\n"
        "    x = a.tobytes()\n"
        '    y = b"".join(parts)\n'
        "    z = a.tobytes()  # noqa\n"
        "    return x, y, z\n"
    )
    hot2 = lib / "shuffle" / "bulk.py"
    hot2.write_text("def g(a):\n    return a.tobytes()\n")
    cold = lib / "shuffle" / "writer.py"
    cold.write_text(
        'def h(a, parts):\n    return a.tobytes(), b"".join(parts)\n'
    )

    findings = []
    for f in (hot, hot2, cold):
        lint.lint_python(f, findings, root=tmp_path)
    py09 = sorted(
        (str(rel), line) for rel, line, code, _m in findings
        if code == "PY09"
    )
    assert py09 == [
        ("sparkrdma_tpu/parallel/exchange.py", 2),
        ("sparkrdma_tpu/parallel/exchange.py", 3),
        ("sparkrdma_tpu/shuffle/bulk.py", 2),
    ], findings


def test_py10_flags_tcp_hot_path_concat(tmp_path):
    """sendall(a + b)-style payload concatenation and per-frame bytes()
    materialization regress the scatter-gather TCP data path; PY10 pins
    them out of transport/tcp.py (noqa escapes)."""
    lint = _load_lint()
    lib = tmp_path / "sparkrdma_tpu"
    (lib / "transport").mkdir(parents=True)

    hot = lib / "transport" / "tcp.py"
    hot.write_text(
        "class C:\n"
        "    def _send_msg(self, opcode, payload):\n"
        "        self._sock.sendall(HDR.pack(opcode) + payload)\n"
        '        self._sock.sendall(b"".join(parts))\n'
        "    def _serve_read(self, payload):\n"
        "        body = bytes(payload)\n"
        "        deliberate = bytes(payload)  # noqa\n"
        "    def _post_read(self, locations, listener):\n"
        "        cold = bytes(locations)\n"
        "        self._sock.sendall(cold)\n"
    )
    cold = lib / "transport" / "loopback.py"
    cold.write_text(
        "def f(sock, a, b):\n"
        "    sock.sendall(a + b)\n"
        "    return bytes(a)\n"
    )

    findings = []
    for f in (hot, cold):
        lint.lint_python(f, findings, root=tmp_path)
    py10 = sorted(
        (str(rel), line) for rel, line, code, _m in findings
        if code == "PY10"
    )
    # line 3: sendall concat; line 4: sendall join; line 6: bytes() in
    # a hot function.  NOT flagged: the noqa'd bytes() (7), bytes()/
    # sendall of a plain name in a non-hot function (9-10), and
    # anything outside transport/tcp.py.
    assert py10 == [
        ("sparkrdma_tpu/transport/tcp.py", 3),
        ("sparkrdma_tpu/transport/tcp.py", 4),
        ("sparkrdma_tpu/transport/tcp.py", 6),
    ], findings
