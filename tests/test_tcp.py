"""TCP transport backend: real sockets, separate networks per manager
(modeling separate processes), and a genuine multi-process shuffle."""

import multiprocessing
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import TcpNetwork

BASE_PORT = 41000


def make_conf(driver_port):
    return TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "10s",
        "spark.shuffle.tpu.connectTimeout": "5s",
    })


@pytest.fixture()
def tcp_cluster():
    """Driver + 2 executors, each with its OWN TcpNetwork instance —
    nothing shared in memory except real sockets."""
    driver_port = BASE_PORT
    conf = make_conf(driver_port)
    driver = TpuShuffleManager(
        conf, is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    executors = [
        TpuShuffleManager(
            make_conf(driver_port), is_driver=False, network=TcpNetwork(),
            port=BASE_PORT + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    yield driver, executors
    for m in executors + [driver]:
        m.stop()


def test_tcp_shuffle_e2e(tcp_cluster):
    driver, executors = tcp_cluster
    num_maps, num_parts = 4, 4
    part = HashPartitioner(num_parts)
    handle = driver.register_shuffle(0, num_maps, part)
    # the driver's registration has to exist on its OWN process only;
    # executors just need the handle object (job scheduler ships it)
    maps_by_host = defaultdict(list)
    records_per_map = [
        [(f"k{j}", (m, j)) for j in range(40)] for m in range(num_maps)
    ]
    for map_id, records in enumerate(records_per_map):
        ex = executors[map_id % 2]
        w = ex.get_writer(handle, map_id)
        w.write(records)
        w.stop(True)
        maps_by_host[ex.local_smid].append(map_id)

    expected = defaultdict(list)
    for recs in records_per_map:
        for k, v in recs:
            expected[k].append(v)

    got = {}
    remote_blocks = 0
    for i, ex in enumerate(executors):
        reader = ex.get_reader(handle, i * 2, i * 2 + 2, dict(maps_by_host))
        for k, v in reader.read():
            got.setdefault(k, []).append(v)
        remote_blocks += reader.metrics.remote_blocks
    assert remote_blocks > 0  # real cross-socket traffic
    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == sorted(expected[k])


def _executor_main(idx, driver_port, my_port, done: multiprocessing.Event,
                   failed: multiprocessing.Event):
    try:
        conf = make_conf(driver_port)
        ex = TpuShuffleManager(
            conf, is_driver=False, network=TcpNetwork(),
            port=my_port, executor_id=str(idx), stage_to_device=False,
        )
        part = HashPartitioner(4)
        handle = ex.register_shuffle(7, 2, part)
        w = ex.get_writer(handle, idx)
        w.write([(f"w{idx}-{j}", j) for j in range(30)])
        w.stop(True)
        # stay alive serving one-sided reads until the driver is done
        done.wait(timeout=60)
        ex.stop()
    except BaseException:
        failed.set()
        raise


def _wait_published(driver, shuffle_id, n, failed, timeout=30):
    """Poll the driver until n map outputs are published (breaking
    early on a child-process failure flag)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if failed.is_set():
            break
        mbh = driver.maps_by_host(shuffle_id)
        if sum(len(v) for v in mbh.values()) == n:
            break
        time.sleep(0.05)
    return driver.maps_by_host(shuffle_id)


def test_tcp_multiprocess_shuffle():
    """Two executor PROCESSES write+publish over sockets; the driver
    process resolves locations and pulls every block."""
    ctx = multiprocessing.get_context("spawn")
    driver_port = BASE_PORT + 500
    conf = make_conf(driver_port)
    driver = TpuShuffleManager(
        conf, is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    part = HashPartitioner(4)
    handle = driver.register_shuffle(7, 2, part)
    done = ctx.Event()
    failed = ctx.Event()
    procs = [
        ctx.Process(
            target=_executor_main,
            args=(i, driver_port, BASE_PORT + 600 + i * 10, done, failed),
            daemon=True,
        )
        for i in range(2)
    ]
    try:
        for p in procs:
            p.start()
        mbh = _wait_published(driver, 7, 2, failed)
        assert not failed.is_set(), "executor subprocess crashed"
        assert sum(len(v) for v in mbh.values()) == 2

        reader = driver.get_reader(handle, 0, 4, mbh)
        got = dict(reader.read())
        assert reader.metrics.remote_blocks > 0
        expected = {}
        for i in range(2):
            for j in range(30):
                expected[f"w{i}-{j}"] = j
        assert got == expected
    finally:
        done.set()
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        driver.stop()


def test_tcp_read_responses_ride_pooled_buffers():
    """Remote TCP fetches land in pooled staging buffers and reach the
    reader as zero-copy slices; the pool reclaims once consumed.
    (Own ports: earlier tests' listeners can linger in TIME_WAIT.)"""
    import gc

    import numpy as np

    driver_port = BASE_PORT + 800
    conf = make_conf(driver_port)
    driver = TpuShuffleManager(
        conf, is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    executors = [
        TpuShuffleManager(
            make_conf(driver_port), is_driver=False, network=TcpNetwork(),
            port=driver_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    part = HashPartitioner(2)
    handle = driver.register_shuffle(9, 1, part)
    w = executors[1].get_writer(handle, 0)
    w.write([(f"k{i}", b"x" * 200) for i in range(500)])
    w.stop(True)
    maps_by_host = {executors[1].local_smid: [0]}

    captured = []
    from sparkrdma_tpu.transport.channel import Channel

    orig = Channel._complete

    def spy(self, listener, result):
        if isinstance(result, list):
            captured.extend(result)
        return orig(self, listener, result)

    Channel._complete = spy
    try:
        reader = executors[0].get_reader(handle, 0, 2, maps_by_host)
        out = list(reader.read())
    finally:
        Channel._complete = orig
    assert len(out) == 500
    blocks = [b for b in captured if isinstance(b, np.ndarray)]
    assert blocks, "remote blocks should be pooled-buffer views"
    assert all(not b.flags.writeable for b in blocks)
    del blocks, captured
    gc.collect()
    assert executors[0].staging_pool.stats()["in_use"] == 0
    for m in executors + [driver]:
        m.stop()


def test_tcp_concurrent_reads_one_channel():
    """Many outstanding reads on ONE channel pair, mixed sizes: the
    read service must not serialize them behind the largest (VERDICT
    round-1 weak #5 — reads are served off the reader thread)."""
    import threading

    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf as Conf
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport.channel import (
        ChannelType,
        FnCompletionListener,
    )
    from sparkrdma_tpu.transport.node import Node
    from sparkrdma_tpu.utils.types import BlockLocation

    net = TcpNetwork()
    a = Node(("127.0.0.1", 42900), Conf())
    b = Node(("127.0.0.1", 42910), Conf())
    net.register(a)
    net.register(b)
    try:
        arena = ArenaManager()
        big = np.arange(8 << 20, dtype=np.uint8) % 251
        small = np.arange(4096, dtype=np.uint8)
        seg_big = arena.register(big, zero_copy_ok=True)
        seg_small = arena.register(small, zero_copy_ok=True)
        b.register_block_store(seg_big.mkey, arena)
        b.register_block_store(seg_small.mkey, arena)
        ch = a.get_channel(b.address, ChannelType.READ_REQUESTOR, net.connect)
        results = {}
        events = [threading.Event() for _ in range(8)]

        def issue(i, loc):
            def ok(blocks, i=i):
                results[i] = bytes(blocks[0])
                events[i].set()

            def err(e, i=i):
                results[i] = e
                events[i].set()

            ch.read_blocks([loc], FnCompletionListener(ok, err))

        issue(0, BlockLocation(0, len(big), seg_big.mkey))
        for i in range(1, 8):
            issue(i, BlockLocation(0, len(small), seg_small.mkey))
        for ev in events:
            assert ev.wait(timeout=30), "read did not complete"
        assert results[0] == bytes(big)
        for i in range(1, 8):
            assert results[i] == bytes(small)
    finally:
        a.stop()
        b.stop()
        net.unregister(a)
        net.unregister(b)


def test_tcp_executor_sigkill_mid_shuffle_fails_promptly():
    """A SIGKILLed executor PROCESS (no goodbye, sockets die) must
    surface as a prompt stage-retriable failure on the data plane —
    never a hang — while the survivor's blocks stay readable.  The
    loopback chaos sweeps cannot exercise real socket death."""
    from sparkrdma_tpu.shuffle.reader import (
        FetchFailedError,
        MetadataFetchFailedError,
    )

    ctx = multiprocessing.get_context("spawn")
    driver_port = BASE_PORT + 700
    conf = make_conf(driver_port)
    driver = TpuShuffleManager(
        conf, is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    part = HashPartitioner(4)
    handle = driver.register_shuffle(7, 2, part)
    # per-process done events: a SIGKILLed child can die holding the
    # shared Event's lock, deadlocking the parent's done.set() in
    # teardown (observed: 90s hang in synchronize.notify) — the
    # victim's event is never touched after the kill
    dones = [ctx.Event(), ctx.Event()]
    failed = ctx.Event()
    killed = False  # whether the SIGKILL landed (victim event unsafe after)
    ports = [BASE_PORT + 1300, BASE_PORT + 1310]
    procs = [
        ctx.Process(
            target=_executor_main,
            args=(i, driver_port, ports[i], dones[i], failed),
            daemon=True,
        )
        for i in range(2)
    ]
    try:
        for p in procs:
            p.start()
        mbh = _wait_published(driver, 7, 2, failed)
        assert not failed.is_set(), "executor subprocess crashed"
        assert sum(len(v) for v in mbh.values()) == 2

        killed = True
        procs[1].kill()  # SIGKILL: no teardown, no goodbye
        procs[1].join(timeout=10)

        t0 = time.monotonic()
        reader = driver.get_reader(handle, 0, 4, mbh)
        with pytest.raises((FetchFailedError, MetadataFetchFailedError)):
            dict(reader.read())
        assert time.monotonic() - t0 < 15, "dead-socket fetch not prompt"

        # the survivor's map output remains fully readable
        mbh0 = {
            smid: mids for smid, mids in mbh.items()
            if smid.block_manager_id.executor_id == "0"
        }
        assert mbh0, mbh
        reader2 = driver.get_reader(handle, 0, 4, mbh0)
        got = dict(reader2.read())
        assert got == {f"w0-{j}": j for j in range(30)}
    finally:
        dones[0].set()
        if not killed:
            # early failure before the kill: release the healthy
            # child instead of stalling 10s and SIGTERMing it
            dones[1].set()
        # after a SIGKILL the victim's event stays untouched (see above)
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        driver.stop()
