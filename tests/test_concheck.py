"""Tier-1 wrapper + unit fixtures for the concurrency gate
(tools/concheck.py): the real tree must be clean, and seeded
violations must each produce exactly their CK finding."""

import importlib.util
import pathlib
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_concheck():
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_concheck", REPO / "tools" / "concheck.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _analyze_src(tmp_path, src: str):
    cc = _load_concheck()
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent(src))
    return cc.analyze([f], root=tmp_path)


def _codes(findings):
    return sorted({code for _rel, _line, code, _msg in findings})


# -- tier-1: the real tree ----------------------------------------------------


def test_library_is_concheck_clean():
    cc = _load_concheck()
    findings = cc.analyze([REPO / "sparkrdma_tpu"])
    assert not findings, "\n".join(
        f"{rel}:{line}: {code} {msg}" for rel, line, code, msg in findings
    )


def test_library_every_lock_is_ranked():
    """CK04-clean AND nonempty: the analyzer actually discovered the
    lock population (a discovery regression would pass vacuously)."""
    cc = _load_concheck()
    an = cc.Analyzer()
    an.analyze_paths([REPO / "sparkrdma_tpu"])
    assert len(an.decls) >= 35, sorted(an.decls)
    assert all(d.rank is not None for d in an.decls.values())


# -- CK01: lock-order cycles --------------------------------------------------


def test_ck01_seeded_lock_order_cycle(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()  # lock-order: 10
                self._b = threading.Lock()  # lock-order: 20

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert _codes(findings) == ["CK01"], findings
    # the inversion anchors at backward()'s inner acquisition
    assert any(line == 15 for _r, line, _c, _m in findings), findings


def test_ck01_nested_nonreentrant_lock(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()  # lock-order: 10

            def deadlock(self):
                with self._a:
                    with self._a:
                        pass
    """)
    assert _codes(findings) == ["CK01"], findings


def test_ck01_through_self_call_closure(tmp_path):
    """The nested-acquisition graph crosses same-class method calls."""
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()  # lock-order: 10
                self._b = threading.Lock()  # lock-order: 20

            def outer(self):
                with self._b:
                    self._helper()

            def _helper(self):
                with self._a:
                    pass
    """)
    assert _codes(findings) == ["CK01"], findings


def test_reentrant_rlock_is_not_a_cycle(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._r = threading.RLock()  # lock-order: 10

            def reenter(self):
                with self._r:
                    self._helper()

            def _helper(self):
                with self._r:
                    pass
    """)
    assert not findings, findings


# -- CK02: blocking while locked ----------------------------------------------


def test_ck02_sendall_under_lock(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self, sock):
                self._lock = threading.Lock()  # lock-order: 10
                self._sock = sock

            def bad(self, data):
                with self._lock:
                    self._sock.sendall(data)

            def fine(self, data):
                self._sock.sendall(data)

            def escaped(self, data):
                with self._lock:
                    self._sock.sendall(data)  # noqa: CK02
    """)
    assert _codes(findings) == ["CK02"], findings
    assert len(findings) == 1 and findings[0][1] == 10, findings


def test_ck02_condition_wait_on_different_lock(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()  # lock-order: 10
                self._cv = threading.Condition()  # lock-order: 20

            def bad(self):
                with self._lock:
                    with self._cv:
                        self._cv.wait()

            def fine(self):
                with self._cv:
                    self._cv.wait()
    """)
    # bad(): waiting on _cv releases only _cv while _lock stays held
    assert "CK02" in _codes(findings), findings
    ck02 = [f for f in findings if f[2] == "CK02"]
    assert len(ck02) == 1 and ck02[0][1] == 11, findings


def test_ck02_event_wait_and_queue_get_under_lock(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()  # lock-order: 10
                self._ev = threading.Event()
                self._q = queue.Queue()

            def bad_wait(self):
                with self._lock:
                    self._ev.wait()

            def bad_get(self):
                with self._lock:
                    return self._q.get()

            def fine_nowait(self):
                with self._lock:
                    return self._q.get_nowait()
    """)
    assert _codes(findings) == ["CK02"], findings
    assert sorted(f[1] for f in findings) == [12, 16], findings


# -- CK03: guarded attributes -------------------------------------------------


def test_ck03_guarded_attribute_escape(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()  # lock-order: 10
                self._items = []  # guarded-by: _lock

            def locked_ok(self):
                with self._lock:
                    self._items.append(1)

            def init_exempt_is_only_for_init(self):
                return len(self._items)

            def escaped(self):
                return list(self._items)  # noqa: CK03
    """)
    assert _codes(findings) == ["CK03"], findings
    assert len(findings) == 1 and findings[0][1] == 13, findings


def test_ck03_unknown_guard_lock_is_flagged(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._items = []  # guarded-by: _nope
    """)
    assert _codes(findings) == ["CK03"], findings


# -- CK04: undeclared locks ---------------------------------------------------


def test_ck04_unranked_lock(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
    """)
    assert _codes(findings) == ["CK04"], findings


def test_ck04_rank_via_dbg_call_and_mismatch(tmp_path):
    findings = _analyze_src(tmp_path, """\
        from sparkrdma_tpu.utils.dbglock import dbg_lock

        class A:
            def __init__(self):
                self._ok = dbg_lock("a.ok", 42)

        class B:
            def __init__(self):
                self._bad = dbg_lock("b.bad", 42)  # lock-order: 13
    """)
    assert _codes(findings) == ["CK04"], findings
    assert len(findings) == 1 and "disagrees" in findings[0][3], findings


def test_ck04_module_level_lock(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading

        _OK = threading.Lock()  # lock-order: 5
        _BAD = threading.Lock()
    """)
    assert _codes(findings) == ["CK04"], findings
    assert len(findings) == 1 and findings[0][1] == 4, findings


def test_nested_class_methods_are_scanned(tmp_path):
    """Classes nested in classes (and in functions) get the full
    treatment — a lock gate that skips helper classes is no gate."""
    findings = _analyze_src(tmp_path, """\
        import threading

        class Outer:
            class Inner:
                def __init__(self):
                    self._lock = threading.Lock()  # lock-order: 10
                    self._state = 0  # guarded-by: _lock

                def deadlock(self):
                    with self._lock:
                        with self._lock:
                            pass

                def unguarded(self):
                    return self._state

        def factory():
            class Local:
                def __init__(self):
                    self._l = threading.Lock()  # lock-order: 20
                    self._v = []  # guarded-by: _l

                def bad(self):
                    self._v.append(1)
            return Local
    """)
    assert _codes(findings) == ["CK01", "CK03"], findings
    ck03_lines = sorted(l for _r, l, c, _m in findings if c == "CK03")
    assert ck03_lines == [15, 24], findings


def test_ck03_applies_to_closures_defined_in_init(tmp_path):
    """A worker closure defined in __init__ runs on another thread —
    the __init__ exemption must not leak into it."""
    findings = _analyze_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()  # lock-order: 10
                self._cache = {}  # guarded-by: _lock
                self._t = threading.Thread(
                    target=lambda: self._cache.clear()
                )

            def guarded(self):
                with self._lock:
                    self._cache.clear()
    """)
    assert _codes(findings) == ["CK03"], findings
    assert findings[0][1] == 8, findings


# -- CK05: blocking in on-loop (event-loop) code ------------------------------


def test_ck05_direct_blocking_in_onloop_method(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import queue

        class H:
            def __init__(self):
                self._q = queue.Queue()

            def on_readable(self):  # on-loop
                return self._q.get()
    """)
    assert _codes(findings) == ["CK05"], findings
    assert findings[0][1] == 8, findings


def test_ck05_transitive_same_class_blocking(tmp_path):
    """An on-loop method calling an unmarked same-class helper that
    blocks is flagged at the CALL site."""
    findings = _analyze_src(tmp_path, """\
        import threading

        class H:
            def __init__(self):
                self._done = threading.Event()

            def on_writable(self):  # on-loop
                self._helper()

            def _helper(self):
                self._done.wait()
    """)
    assert _codes(findings) == ["CK05"], findings
    assert findings[0][1] == 8, findings


def test_ck05_sleep_and_condition_wait_flagged_on_loop_only(tmp_path):
    """time.sleep and own-condition waits block an event loop (CK05)
    but are NOT CK02 findings off-loop — pre-CK05 behavior kept."""
    findings = _analyze_src(tmp_path, """\
        import threading
        import time

        class H:
            def __init__(self):
                self._cv = threading.Condition()  # lock-order: 10

            def on_readable(self):  # on-loop
                time.sleep(0.1)

            def on_writable(self):  # on-loop
                with self._cv:
                    self._cv.wait()

            def worker(self):
                time.sleep(0.1)
                with self._cv:
                    self._cv.wait()
    """)
    assert _codes(findings) == ["CK05"], findings
    assert sorted(l for _r, l, _c, _m in findings) == [9, 13], findings


def test_ck05_nonblocking_socket_ops_allowed_on_loop(tmp_path):
    """recv_into/sendmsg/accept are the loop's job — no finding, and
    a code-scoped noqa silences a deliberate violation."""
    findings = _analyze_src(tmp_path, """\
        import queue

        class H:
            def __init__(self):
                self._q = queue.Queue()

            def on_readable(self):  # on-loop
                try:
                    n = self._sock.recv_into(self._buf)
                    self._sock.sendmsg([self._buf])
                    self._sock.accept()
                except BlockingIOError:
                    n = 0
                return n

            def on_writable(self):  # on-loop
                return self._q.get()  # noqa: CK05
    """)
    assert findings == [], findings
