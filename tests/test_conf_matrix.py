"""Cross-product e2e sweep over the conf knobs that select storage and
wire formats.  Each individual knob has focused tests; this matrix
exists for the INTERACTIONS (spill x compression x directIO x
serializer x op) — the reference gets the analogous coverage for free
from Spark's own conf-matrix CI, which this repo must supply itself
(SURVEY.md §4: no tests exist upstream to port)."""

import numpy as np
import pytest

from sparkrdma_tpu.api import TpuShuffleContext
from sparkrdma_tpu.conf import TpuShuffleConf

OPS = ("group", "reduce", "sort")


def _oracle(records, op):
    if op == "reduce":
        out = {}
        for k, v in records:
            out[k] = out.get(k, 0) + v
        return sorted(out.items())
    if op == "sort":
        return sorted(records, key=lambda kv: kv[0])
    out = {}
    for k, v in records:
        out.setdefault(k, []).append(v)
    return {k: sorted(vs) for k, vs in out.items()}


def _run(ds, op, columnar=False):
    if op == "reduce":
        # the string form keeps the columnar plane on its vectorized
        # ColumnarAggregator path; a Python lambda would silently
        # degrade the columnar cells to the tuple plane
        f = "sum" if columnar else (lambda a, b: a + b)
        return sorted(ds.reduce_by_key(f, num_partitions=3).collect())
    if op == "sort":
        return ds.sort_by_key(num_partitions=3).collect()
    got = ds.group_by_key(num_partitions=3).collect()
    return {
        k: sorted(v.tolist() if isinstance(v, np.ndarray) else list(v))
        for k, v in got
    }


@pytest.mark.parametrize("serializer", ["pickle", "columnar"])
@pytest.mark.parametrize("compress", [False, True])
@pytest.mark.parametrize("spill", [False, True])
@pytest.mark.parametrize("direct_io", ["auto", "off"])
def test_conf_matrix_e2e(tmp_path, serializer, compress, spill, direct_io):
    n = 1500
    rng = np.random.default_rng(42)
    keys = rng.integers(0, 40, n).astype(np.int64)
    vals = rng.integers(0, 1000, n).astype(np.int64)
    records = list(zip(keys.tolist(), vals.tolist()))
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.serializer": serializer,
        "spark.shuffle.tpu.compress": str(compress).lower(),
        "spark.shuffle.tpu.directIO": direct_io,
        "spark.shuffle.tpu.spillDir": str(tmp_path),
        **(
            {"spark.shuffle.tpu.shuffleSpillRecordThreshold": "200"}
            if spill else {}
        ),
    })
    with TpuShuffleContext(num_executors=2, conf=conf,
                           stage_to_device=False) as ctx:
        for op in OPS:
            if serializer == "columnar":
                ds = ctx.parallelize_columns(keys, vals, num_slices=4)
            else:
                ds = ctx.parallelize(records, num_slices=4)
            got = _run(ds, op, columnar=serializer == "columnar")
            want = _oracle(records, op)
            if op == "group":
                assert {int(k): v for k, v in got.items()} == want, (
                    serializer, compress, spill, direct_io, op
                )
            elif op == "sort":
                # sort_by_key guarantees key order; values within a key
                # may arrive in any order across planes
                assert [int(k) for k, _ in got] == [k for k, _ in want]
                bykey = {}
                for k, v in got:
                    bykey.setdefault(int(k), []).append(int(v))
                wkey = {}
                for k, v in want:
                    wkey.setdefault(k, []).append(v)
                assert {k: sorted(v) for k, v in bykey.items()} == {
                    k: sorted(v) for k, v in wkey.items()
                }
            else:
                assert [(int(k), int(v)) for k, v in got] == want, (
                    serializer, compress, spill, direct_io, op
                )
    # no spill or shuffle files may leak once the context closes
    leaked = [p for p in tmp_path.iterdir() if p.name.startswith("sparkrdma")]
    assert not leaked, leaked
