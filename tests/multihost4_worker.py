"""Worker for the 4-process unified-plane test (test_multihost.py).

Run as: python multihost4_worker.py <process_id> <coordinator_port>

Scales the windowed read plane's cross-process proof from 2 to 4 OS
processes: 4 executors over a 4-device global mesh (one device per
process), a TCP control plane, uneven plan windows (8 maps, window of
3 → 3/3/2), reducer-issued per-partition reads, and the straggler
overlap — window 0's collective completes on every host while each
process's second map is still unwritten.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N_PROCS = 4
NUM_PARTS = 8
NUM_MAPS = 8
SHUFFLE = 73


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    import threading
    import time

    import numpy as np
    from jax.sharding import Mesh

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.parallel import multihost
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane
    from sparkrdma_tpu.shuffle.manager import ShuffleHandle, TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import TcpNetwork

    driver_port = int(port) + 41
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
        "spark.shuffle.tpu.connectTimeout": "10s",
        "spark.shuffle.tpu.bulkWindowMaps": "3",
        "spark.shuffle.tpu.readPlane": "windowed",
    })
    part = HashPartitioner(NUM_PARTS)
    driver = None
    if pid == 0:
        driver = TpuShuffleManager(
            conf, is_driver=True, network=TcpNetwork(), port=driver_port,
        )
        driver.register_shuffle(SHUFFLE, NUM_MAPS, part)

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=N_PROCS,
        process_id=pid,
    )
    assert jax.process_count() == N_PROCS, jax.process_count()

    ex_mgr = TpuShuffleManager(
        conf, is_driver=False, network=TcpNetwork(),
        port=driver_port + 10 + pid, executor_id=str(pid),
    )
    deadline = time.time() + 60
    while time.time() < deadline and len(ex_mgr._peers) < N_PROCS:
        time.sleep(0.02)
    assert len(ex_mgr._peers) == N_PROCS, "announce did not reach everyone"

    # one mesh device per process, ordered by process index — every
    # process derives the identical mesh, matching the plan's canonical
    # host order (ports ascend with pid)
    per_proc = {}
    for dev in jax.devices():
        per_proc.setdefault(dev.process_index, dev)
    mesh = Mesh(
        np.array([per_proc[i] for i in sorted(per_proc)]),
        (EXCHANGE_AXIS,),
    )
    ex_mgr.windowed_plane = WindowedReadPlane(
        ex_mgr, exchange=TileExchange(mesh, tile_bytes=1 << 12)
    )

    handle = ShuffleHandle(SHUFFLE, NUM_MAPS, part)
    recs = {
        m: [(f"q{m}-k{j}", (m, j)) for j in range(40)]
        for m in range(NUM_MAPS)
    }
    w = ex_mgr.get_writer(handle, pid)
    w.write(recs[pid])
    w.stop(True)

    my_parts = [r for r in range(NUM_PARTS) if r % N_PROCS == pid]
    results = {}
    errors = {}

    def reduce_task(p):
        try:
            r = ex_mgr.get_reader(handle, p, p + 1, {})
            results[p] = list(r.read())
        except BaseException as e:
            errors[p] = e

    threads = [
        threading.Thread(target=reduce_task, args=(p,), daemon=True)
        for p in my_parts
    ]
    for t in threads:
        t.start()
    deadline = time.time() + 60
    while (time.time() < deadline
           and not ex_mgr.windowed_plane.window_events(SHUFFLE)):
        time.sleep(0.02)
    assert ex_mgr.windowed_plane.window_events(SHUFFLE), (
        f"proc {pid}: no window landed before the stragglers"
    )
    assert not results, (
        f"proc {pid}: a reducer finished before the straggler maps"
    )

    w = ex_mgr.get_writer(handle, pid + N_PROCS)
    w.write(recs[pid + N_PROCS])
    w.stop(True)
    for t in threads:
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads), f"proc {pid}: hung reducer"
    assert not errors, f"proc {pid}: {errors!r}"
    # 8 maps / window of 3 → windows 3/3/2 on every host
    wins = [wn for wn, _t, _b in ex_mgr.windowed_plane.window_events(SHUFFLE)]
    assert wins == [0, 1, 2], f"proc {pid}: windows {wins}"
    all_recs = [kv for m in range(NUM_MAPS) for kv in recs[m]]
    for p in my_parts:
        expect = [(k, v) for k, v in all_recs if part.partition(k) == p]
        assert sorted(results.get(p, [])) == sorted(expect), (
            f"proc {pid}: partition {p}: "
            f"{len(results.get(p, []))} != {len(expect)}"
        )

    ex_mgr.stop()
    if driver is not None:
        driver.stop()

    print(f"proc {pid}: 4-process windowed plane OK", flush=True)


if __name__ == "__main__":
    main()
