"""Worker for the 4-process unified-plane test (test_multihost.py).

Run as: python multihost4_worker.py <process_id> <coordinator_port>

Scales the windowed read plane's cross-process proof from 2 to 4 OS
processes: 4 executors over a 4-device global mesh (one device per
process), a TCP control plane, uneven plan windows (8 maps, window of
3 → 3/3/2), reducer-issued per-partition reads, and the straggler
overlap — window 0's collective completes on every host while each
process's second map is still unwritten.

Phase 2 (induced executor loss, VERDICT r4 item 3): a second windowed
shuffle is registered, process 3 SIGKILLs itself at a seeded random
moment before any map is written, and every survivor's pending
windowed reader must fail PROMPTLY with a stage-retriable error — the
driver's heartbeat monitor prunes the dead executor over real TCP and
the membership-epoch bump dooms the pending window-plan waiters
(manager.py _membership_epoch).  On a real pod a dead host kills the
mesh's collectives, so prompt stage failure (world relaunch, lineage
retry) IS the contract — matching the reference, where a torn-down QP
fails the fetch iterator into Spark's stage retry
(RdmaShuffleFetcherIterator.scala:368-373).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N_PROCS = 4
NUM_PARTS = 8
NUM_MAPS = 8
SHUFFLE = 73
LOSS_SHUFFLE = 91
VICTIM = 3


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    import threading
    import time

    import numpy as np
    from jax.sharding import Mesh

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.parallel import multihost
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane
    from sparkrdma_tpu.shuffle.manager import ShuffleHandle, TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import TcpNetwork

    driver_port = int(port) + 41
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
        "spark.shuffle.tpu.connectTimeout": "10s",
        "spark.shuffle.tpu.bulkWindowMaps": "3",
        "spark.shuffle.tpu.readPlane": "windowed",
        # phase 2 relies on the monitor pruning the SIGKILLed executor
        # fast enough that "prompt stage failure" means seconds —
        # but the timeout must ride out multi-second ack starvation
        # while 4 processes share one core through XLA compiles and
        # the Gloo rendezvous (200ms/1s falsely pruned ALL executors)
        "spark.shuffle.tpu.heartbeatInterval": "500ms",
        "spark.shuffle.tpu.heartbeatTimeout": "8s",
    })
    part = HashPartitioner(NUM_PARTS)
    driver = None
    if pid == 0:
        driver = TpuShuffleManager(
            conf, is_driver=True, network=TcpNetwork(), port=driver_port,
        )
        driver.register_shuffle(SHUFFLE, NUM_MAPS, part)
        driver.register_shuffle(LOSS_SHUFFLE, NUM_MAPS, part)

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=N_PROCS,
        process_id=pid,
    )
    assert jax.process_count() == N_PROCS, jax.process_count()

    ex_mgr = TpuShuffleManager(
        conf, is_driver=False, network=TcpNetwork(),
        port=driver_port + 10 + pid, executor_id=str(pid),
    )
    deadline = time.time() + 60
    while time.time() < deadline and len(ex_mgr._peers) < N_PROCS:
        time.sleep(0.02)
    assert len(ex_mgr._peers) == N_PROCS, "announce did not reach everyone"

    # one mesh device per process, ordered by process index — every
    # process derives the identical mesh, matching the plan's canonical
    # host order (ports ascend with pid)
    per_proc = {}
    for dev in jax.devices():
        per_proc.setdefault(dev.process_index, dev)
    mesh = Mesh(
        np.array([per_proc[i] for i in sorted(per_proc)]),
        (EXCHANGE_AXIS,),
    )
    ex_mgr.windowed_plane = WindowedReadPlane(
        ex_mgr, exchange=TileExchange(mesh, tile_bytes=1 << 12)
    )

    handle = ShuffleHandle(SHUFFLE, NUM_MAPS, part)
    recs = {
        m: [(f"q{m}-k{j}", (m, j)) for j in range(40)]
        for m in range(NUM_MAPS)
    }
    w = ex_mgr.get_writer(handle, pid)
    w.write(recs[pid])
    w.stop(True)

    my_parts = [r for r in range(NUM_PARTS) if r % N_PROCS == pid]
    results = {}
    errors = {}

    def reduce_task(p):
        try:
            r = ex_mgr.get_reader(handle, p, p + 1, {})
            results[p] = list(r.read())
        except BaseException as e:
            errors[p] = e

    threads = [
        threading.Thread(target=reduce_task, args=(p,), daemon=True)
        for p in my_parts
    ]
    for t in threads:
        t.start()
    deadline = time.time() + 60
    while (time.time() < deadline
           and not ex_mgr.windowed_plane.window_events(SHUFFLE)):
        time.sleep(0.02)
    assert ex_mgr.windowed_plane.window_events(SHUFFLE), (
        f"proc {pid}: no window landed before the stragglers"
    )
    assert not results, (
        f"proc {pid}: a reducer finished before the straggler maps"
    )

    w = ex_mgr.get_writer(handle, pid + N_PROCS)
    w.write(recs[pid + N_PROCS])
    w.stop(True)
    for t in threads:
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads), f"proc {pid}: hung reducer"
    assert not errors, f"proc {pid}: {errors!r}"
    # 8 maps / window of 3 → windows 3/3/2 on every host
    wins = [wn for wn, _t, _b in ex_mgr.windowed_plane.window_events(SHUFFLE)]
    assert wins == [0, 1, 2], f"proc {pid}: windows {wins}"
    all_recs = [kv for m in range(NUM_MAPS) for kv in recs[m]]
    for p in my_parts:
        expect = [(k, v) for k, v in all_recs if part.partition(k) == p]
        assert sorted(results.get(p, [])) == sorted(expect), (
            f"proc {pid}: partition {p}: "
            f"{len(results.get(p, []))} != {len(expect)}"
        )

    print(f"proc {pid}: 4-process windowed plane OK", flush=True)

    # ---- phase 2: induced executor loss ---------------------------------
    import random
    import signal

    from sparkrdma_tpu.shuffle.reader import (
        FetchFailedError,
        MetadataFetchFailedError,
    )

    rng = random.Random(
        int(os.environ.get("SPARKRDMA_TEST_CHAOS_SEED", "4091")) + pid
    )
    handle2 = ShuffleHandle(LOSS_SHUFFLE, NUM_MAPS, part)
    if pid == VICTIM:
        # die without goodbye at a seeded random moment — before any
        # map of LOSS_SHUFFLE is written, so no window plan can strand
        # a survivor inside a collective missing this (dead) member
        time.sleep(rng.uniform(0.0, 0.5))
        os.kill(os.getpid(), signal.SIGKILL)

    loss_errors = {}
    loss_done = {}

    def loss_reduce(p):
        try:
            r = ex_mgr.get_reader(handle2, p, p + 1, {})
            loss_done[p] = list(r.read())
        except (FetchFailedError, MetadataFetchFailedError) as e:
            loss_errors[p] = e

    lthreads = [
        threading.Thread(target=loss_reduce, args=(p,), daemon=True)
        for p in my_parts
    ]
    t0 = time.time()
    for t in lthreads:
        t.start()
    for t in lthreads:
        t.join(timeout=45)
    assert not any(t.is_alive() for t in lthreads), (
        f"proc {pid}: windowed reader HUNG after executor loss"
    )
    assert not loss_done, (
        f"proc {pid}: reader returned data for a shuffle whose maps "
        f"never ran: {loss_done}"
    )
    assert set(loss_errors) == set(my_parts), (
        f"proc {pid}: missing stage-retriable failures: {loss_errors}"
    )
    elapsed = time.time() - t0
    assert elapsed < 40, (
        f"proc {pid}: loss failure took {elapsed:.1f}s — not prompt"
    )
    print(f"proc {pid}: windowed executor-loss fails prompt OK",
          flush=True)
    # the mesh lost a member: the jax distributed runtime cannot
    # barrier at interpreter exit, so leave without atexit teardown
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
