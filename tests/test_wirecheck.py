"""Tier-1 wrapper + unit fixtures for the wire-protocol conformance
gate (tools/wirecheck.py): the real tree must be clean with a nonempty
schema census, and seeded wire-contract violations must each produce
exactly their WC finding."""

import importlib.util
import pathlib
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_wirecheck():
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_wirecheck", REPO / "tools" / "wirecheck.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _analyze_src(tmp_path, src: str):
    wc = _load_wirecheck()
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent(src))
    return wc.analyze([f], root=tmp_path)


def _codes(findings):
    return sorted(code for _rel, _line, code, _msg in findings)


# -- tier-1: the real tree ----------------------------------------------------


def test_wire_surface_is_wirecheck_clean():
    wc = _load_wirecheck()
    findings = wc.analyze(wc.DEFAULT_PATHS)
    assert not findings, "\n".join(
        f"{rel}:{line}: {code} {msg}" for rel, line, code, msg in findings
    )


def test_wire_census_is_nonempty():
    """Clean AND nonempty: the analyzer actually discovered the wire
    population (a discovery regression would pass vacuously).  Floor:
    the 12 message schemas, the MSG_TYPES registry, the 3 transport
    opcodes."""
    wc = _load_wirecheck()
    an = wc.Analyzer()
    findings = an.analyze_paths(wc.DEFAULT_PATHS)
    assert not findings
    assert an.schema_count >= 12, an.schema_count
    n_reg = sum(len(m.registry or ()) for m in an.modules.values())
    n_ops = sum(len(m.op_consts) for m in an.modules.values())
    assert n_reg >= 12, n_reg
    assert n_ops >= 3, n_ops
    assert len(an.struct_fmts) >= 10, sorted(an.struct_fmts)


# -- WC01: pack/unpack asymmetry ----------------------------------------------


def test_wc01_non_little_endian_format(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        HDR = struct.Struct(">iB")
    """)
    assert _codes(findings) == ["WC01"]


def test_wc01_native_endianness_format(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        def enc(a):
            return struct.pack("ii", a, a)
    """)
    assert _codes(findings) == ["WC01"]


def test_wc01_pack_arity_mismatch(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        PAIR = struct.Struct("<ii")
        def enc(a):
            return PAIR.pack(a)
    """)
    assert _codes(findings) == ["WC01"]


def test_wc01_unpack_target_count_mismatch(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        PAIR = struct.Struct("<ii")
        def dec(buf):
            a, b, c = PAIR.unpack_from(buf, 0)
            return a, b, c
    """)
    assert _codes(findings) == ["WC01"]


def test_wc01_matched_arity_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        PAIR = struct.Struct("<4sBHH")
        def enc(m, c, p, v):
            return PAIR.pack(m, c, p, v)
        def dec(buf):
            m, c, p, v = PAIR.unpack_from(buf, 0)
            return m, c, p, v
    """)
    assert findings == []


def test_wc01_derived_schema_shadowed_by_handwritten_codec(tmp_path):
    findings = _analyze_src(tmp_path, """
        class Msg:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.i32("x"),)

            def _payload(self):
                return b""
    """)
    assert _codes(findings) == ["WC01"]


def test_wc01_custom_schema_missing_codec_half(tmp_path):
    findings = _analyze_src(tmp_path, """
        class Msg:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.custom("x", "<i"),)

            def _payload(self):
                return b""
    """)
    # missing _decode_payload AND _payload_size
    assert _codes(findings) == ["WC01", "WC01"]


def test_wc01_custom_codec_asymmetry(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct

        class Msg:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.custom("x", "<i"),)

            def _payload(self):
                return struct.pack("<i", self.x)

            def _payload_size(self):
                return 8

            @staticmethod
            def _decode_payload(view):
                (x,) = struct.unpack_from("<q", view, 0)
                return Msg(x)
    """)
    # encoder writes '<i' never read; decoder reads '<q' never written
    assert _codes(findings) == ["WC01", "WC01"]


def test_wc01_symmetric_custom_codec_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct

        class Msg:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.custom("xs", "<i count + count * <q"),)

            def _payload(self):
                buf = bytearray(struct.pack("<i", len(self.xs)))
                for x in self.xs:
                    buf += struct.pack("<q", x)
                return bytes(buf)

            def _payload_size(self):
                return 4 + 8 * len(self.xs)

            @staticmethod
            def _decode_payload(view):
                (n,) = struct.unpack_from("<i", view, 0)
                if n * 8 > len(view):
                    raise ValueError("count overruns buffer")
                xs = struct.unpack_from(f"<{n}q", view, 4)
                return Msg(xs)
    """)
    assert findings == []


# -- WC02: MSG_TYPE registry integrity ----------------------------------------


def test_wc02_duplicate_msg_type(tmp_path):
    findings = _analyze_src(tmp_path, """
        class A:
            MSG_TYPE = 5
            WIRE_SCHEMA = (F.i32("x"),)

        class B:
            MSG_TYPE = 5
            WIRE_SCHEMA = (F.i32("y"),)
    """)
    assert _codes(findings) == ["WC02"]


def test_wc02_unregistered_message_class(tmp_path):
    findings = _analyze_src(tmp_path, """
        class A:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.i32("x"),)

        class B:
            MSG_TYPE = 2
            WIRE_SCHEMA = (F.i32("y"),)

        MSG_TYPES = {1: A}
    """)
    assert _codes(findings) == ["WC02"]


def test_wc02_registered_type_without_dispatch_handler(tmp_path):
    findings = _analyze_src(tmp_path, """
        class A:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.i32("x"),)

        class B:
            MSG_TYPE = 2
            WIRE_SCHEMA = (F.i32("y"),)

        MSG_TYPES = {1: A, 2: B}

        def _receive(node, msg):
            if isinstance(msg, A):
                return node.on_a(msg)
    """)
    assert _codes(findings) == ["WC02"]


def test_wc02_full_registry_and_dispatch_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        class A:
            MSG_TYPE = 1
            WIRE_SCHEMA = (F.i32("x"),)

        class B:
            MSG_TYPE = 2
            WIRE_SCHEMA = (F.i32("y"),)

        MSG_TYPES = {1: A, 2: B}

        def _receive(node, msg):
            if isinstance(msg, (A, B)):
                return node.handle(msg)
    """)
    assert findings == []


# -- WC03: opcode/handler parity across engines -------------------------------


def test_wc03_dead_opcode(tmp_path):
    findings = _analyze_src(tmp_path, """
        OP_RPC = 1
        OP_GHOST = 2

        def _read_loop(self):
            op = self.next_op()
            if op == OP_RPC:
                self.on_rpc()
    """)
    assert _codes(findings) == ["WC03"]


def test_wc03_async_engine_missing_opcode(tmp_path):
    findings = _analyze_src(tmp_path, """
        OP_RPC = 1
        OP_READ = 2

        def _read_loop(self):
            op = self.next_op()
            if op == OP_RPC:
                self.on_rpc()
            elif op == OP_READ:
                self.on_read()

        def _rx_dispatch(self):
            op = self.next_op()
            if op == OP_RPC:
                self.on_rpc()
    """)
    assert _codes(findings) == ["WC03"]


def test_wc03_loopback_without_analogs(tmp_path):
    findings = _analyze_src(tmp_path, """
        OP_RPC = 1

        def _read_loop(self):
            op = self.next_op()
            if op == OP_RPC:
                self.on_rpc()

        class LoopbackChannel:
            def send(self, frame):
                self.peer.deliver(frame)
    """)
    # no dispatch_frame analog AND no read_local_blocks analog
    assert _codes(findings) == ["WC03", "WC03"]


def test_wc03_parity_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        OP_RPC = 1
        OP_READ = 2

        def _read_loop(self):
            op = self.next_op()
            if op == OP_RPC:
                self.on_rpc()
            elif op == OP_READ:
                self.on_read()

        def _rx_dispatch(self):
            op = self.next_op()
            if op == OP_RPC:
                self.on_rpc()
            elif op == OP_READ:
                self.on_read()

        class LoopbackChannel:
            def _deliver(self, frame):
                self.remote.dispatch_frame(self, frame)

            def _serve(self, req):
                return self.node.read_local_blocks(req)
    """)
    assert findings == []


# -- WC04: hand-written magic sizes -------------------------------------------


def test_wc04_literal_size_constant(tmp_path):
    findings = _analyze_src(tmp_path, """
        HEADER_SIZE = 8
    """)
    assert _codes(findings) == ["WC04"]


def test_wc04_offset_advanced_by_literal(tmp_path):
    findings = _analyze_src(tmp_path, """
        def dec(buf):
            off = 0
            off += 8
            return buf[off]
    """)
    assert _codes(findings) == ["WC04"]


def test_wc04_struct_derived_size_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        HDR = struct.Struct("<ii")
        HEADER_SIZE = HDR.size

        def dec(buf):
            off = 0
            off += HDR.size
            return buf[off]
    """)
    assert findings == []


# -- WC05: bounds discipline --------------------------------------------------


def test_wc05_unguarded_count_sizes_a_loop(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        CNT = struct.Struct("<i")

        def dec(buf):
            (n,) = CNT.unpack_from(buf, 0)
            return [read_one(buf, i) for i in range(n)]
    """)
    assert _codes(findings) == ["WC05"]


def test_wc05_unguarded_length_sizes_a_slice(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        LEN = struct.Struct("<I")

        def dec(buf, off):
            (n,) = LEN.unpack_from(buf, off)
            end = off + n
            return buf[off:end]
    """)
    assert _codes(findings) == ["WC05"]


def test_wc05_guard_call_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        CNT = struct.Struct("<i")

        def dec(buf):
            (n,) = CNT.unpack_from(buf, 0)
            _check_count(n, 4, buf, CNT.size)
            return [read_one(buf, i) for i in range(n)]
    """)
    assert findings == []


def test_wc05_if_guard_that_raises_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        CNT = struct.Struct("<i")

        def dec(buf):
            (n,) = CNT.unpack_from(buf, 0)
            if n < 0 or n > len(buf):
                raise ValueError("count overruns buffer")
            return bytearray(n)
    """)
    assert findings == []


def test_wc05_try_containment_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        CNT = struct.Struct("<i")

        def dec(buf):
            try:
                (n,) = CNT.unpack_from(buf, 0)
                return bytearray(n)
            except (ValueError, MemoryError):
                return None
    """)
    assert findings == []


def test_wc05_noqa_escape(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        CNT = struct.Struct("<i")

        def dec(buf):
            (n,) = CNT.unpack_from(buf, 0)
            return bytearray(n)  # noqa: WC05
    """)
    assert findings == []


def test_wrong_noqa_code_does_not_suppress(tmp_path):
    findings = _analyze_src(tmp_path, """
        import struct
        CNT = struct.Struct("<i")

        def dec(buf):
            (n,) = CNT.unpack_from(buf, 0)
            return bytearray(n)  # noqa: WC01
    """)
    assert _codes(findings) == ["WC05"]
