"""Multi-controller (multi-host analog) integration: two real processes
rendezvous through ``multihost.initialize`` and run cross-process
collectives — psum and the tiled all_to_all the shuffle exchange rides —
over a global mesh (SURVEY.md §2 distributed-backend inventory row; on a
pod the same code paths carry ICI in-slice and DCN across slices)."""

import os
import socket
import subprocess
import sys

import pytest

from sparkrdma_tpu.parallel.multihost import (
    supports_multiprocess_collectives,
)

# Collection-time gate (the supports_pallas_partition_id precedent):
# the workers strip the harness's JAX_PLATFORMS/XLA_FLAGS pins and get
# jax's real default backend — on a CPU-only host that backend cannot
# run cross-process collectives, so these tests skip with the reason
# spelled out instead of failing 150-240s into a doomed rendezvous.
pytestmark = pytest.mark.skipif(
    not supports_multiprocess_collectives(),
    reason="default jax backend has no multiprocess collectives "
    "(CPU backend: 'Multiprocess computations aren't implemented') — "
    "needs a real TPU/GPU multi-controller runtime",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(worker_file: str, n_procs: int, timeout: int,
                 ok_msg: str, sigkilled: dict = {}) -> None:
    """``sigkilled`` maps a process id that SIGKILLs itself mid-run to
    the ok-message it must have printed BEFORE dying (its exit code is
    then -SIGKILL, not 0)."""
    import signal

    worker = os.path.join(os.path.dirname(__file__), worker_file)
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(n_procs)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{worker_file} hung")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if pid in sigkilled:
            assert p.returncode == -signal.SIGKILL, (
                f"victim proc {pid} exited {p.returncode}, "
                f"expected SIGKILL:\n{out}"
            )
            assert f"proc {pid}: {sigkilled[pid]}" in out, out
            continue
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid}: {ok_msg}" in out, out


def test_two_process_collectives():
    _run_workers(
        "multihost_worker.py", 2, 150, "multihost collectives OK"
    )


def test_four_process_windowed_plane():
    """The unified plane at 4 OS processes: uneven plan windows,
    reducer-issued reads, straggler overlap — then an INDUCED EXECUTOR
    LOSS (process 3 SIGKILLs itself) whose pending windowed readers
    must fail promptly on every survivor via heartbeat prune +
    membership-epoch plan dooming over real TCP."""
    _run_workers(
        "multihost4_worker.py", 4, 240,
        "windowed executor-loss fails prompt OK",
        sigkilled={3: "4-process windowed plane OK"},
    )
