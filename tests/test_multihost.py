"""Multi-controller (multi-host analog) integration: two real processes
rendezvous through ``multihost.initialize`` and run cross-process
collectives — psum and the tiled all_to_all the shuffle exchange rides —
over a global mesh (SURVEY.md §2 distributed-backend inventory row; on a
pod the same code paths carry ICI in-slice and DCN across slices)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_collectives():
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker hung")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid}: multihost collectives OK" in out, out
