"""Observability plane (obs/): distributed trace propagation over the
wire, flight-recorder ring/dump mechanics, negotiated wire-version
fallback in both directions, the /health and /flightrecorder
endpoints, and fleet-wide dump collection merging a 2-process run into
one cross-process trace."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from collections import defaultdict

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY, counter
from sparkrdma_tpu.obs import RECORDER, TRACING, fr_event
from sparkrdma_tpu.obs.collect import merge_dumps, merged_events, write_dump
from sparkrdma_tpu.qos.http import MetricsHttpServer
from sparkrdma_tpu.qos.registry import GLOBAL_QOS
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import FetchFailedError
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.transport import tcp as wire
from sparkrdma_tpu.transport.channel import ChannelType, FnCompletionListener
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.transport.simfleet import SimPeerFleetProc
from sparkrdma_tpu.utils.types import BlockLocation

BASE_PORT = 34200
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_REPORT = os.path.join(ROOT, "tools", "trace_report.py")


@pytest.fixture(autouse=True)
def obs_reset():
    """Every test leaves the process-global observability planes the
    way it found them (owner counts, registries)."""
    prev_metrics = GLOBAL_REGISTRY.enabled
    GLOBAL_QOS.reset()
    yield
    GLOBAL_REGISTRY.enabled = prev_metrics
    GLOBAL_QOS.enabled = False
    GLOBAL_QOS.reset()
    while RECORDER.enabled:
        RECORDER.release()
    while TRACING.enabled:
        TRACING.release()


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=5) as resp:
        assert resp.status == 200
        return resp.read()


# -- trace context ------------------------------------------------------------


def test_tracing_off_is_none_and_zero_cost():
    assert not TRACING.enabled
    assert TRACING.start() is None


def test_tracing_start_child_and_sampling():
    TRACING.retain(1.0)
    try:
        a, b = TRACING.start(), TRACING.start()
        assert a is not None and b is not None
        assert a.trace_id != b.trace_id
        assert a.trace_id != 0 and a.span_id != 0
        child = a.child()
        assert child.trace_id == a.trace_id
        assert child.span_id != a.span_id
    finally:
        TRACING.release()
    # rate 0: enabled but every start sampled out
    TRACING.retain(0.0)
    try:
        assert all(TRACING.start() is None for _ in range(8))
    finally:
        TRACING.release()
    # rate 0.5 -> stride 2: exactly every other start traces
    TRACING.retain(0.5)
    try:
        got = [TRACING.start() is not None for _ in range(8)]
        assert sum(got) == 4
    finally:
        TRACING.release()


# -- flight-recorder rings ----------------------------------------------------


def test_recorder_off_fr_event_is_noop():
    assert not RECORDER.enabled
    fr_event("reader", "fetch_issue", bytes=1)  # must not raise
    assert RECORDER._rings == {} or not RECORDER.enabled


def test_ring_overflow_drops_oldest_and_counts():
    GLOBAL_REGISTRY.enabled = True
    base = counter("obs_events_dropped_total", plane="qos").value
    RECORDER.retain(ring_size=64)
    try:
        for i in range(100):
            fr_event("qos", "credit_block", pool="serve", bytes=i)
        snap = RECORDER.snapshot()
        ring = snap["planes"]["qos"]
        assert len(ring["events"]) == 64
        assert ring["dropped"] == 36
        # the ring kept the NEWEST 64: the oldest surviving event is #36
        assert ring["events"][0][2]["bytes"] == 36
        assert counter(
            "obs_events_dropped_total", plane="qos"
        ).value - base == 36
    finally:
        RECORDER.release()


def test_recorder_retain_is_owner_counted():
    RECORDER.retain(ring_size=64)
    RECORDER.retain(ring_size=64)
    RECORDER.release()
    assert RECORDER.enabled  # one owner still holds it
    RECORDER.release()
    assert not RECORDER.enabled


def test_dump_and_auto_dump_rate_cap(tmp_path):
    GLOBAL_REGISTRY.enabled = True
    RECORDER.retain(ring_size=64, dump_dir=str(tmp_path))
    try:
        fr_event("faults", "breaker_trip", peer="p1", strikes=3)
        p1 = RECORDER.auto_dump("breaker_trip")
        assert p1 is not None and os.path.exists(p1)
        assert "breaker_trip" in os.path.basename(p1)
        doc = json.load(open(p1))
        assert doc["reason"] == "breaker_trip"
        assert doc["pid"] == os.getpid()
        names = [e[1] for e in doc["planes"]["faults"]["events"]]
        assert "breaker_trip" in names
        # second auto-dump inside the interval is suppressed
        assert RECORDER.auto_dump("breaker_trip") is None
        # explicit dump is never rate-capped
        p2 = RECORDER.dump("on_demand")
        assert p2 is not None and p2 != p1
    finally:
        RECORDER.release()


# -- /health and /flightrecorder ----------------------------------------------


def test_health_and_flightrecorder_endpoints():
    srv = MetricsHttpServer(0)
    RECORDER.retain(ring_size=64)
    try:
        health = json.loads(_get(srv.url("/health")))
        assert health["status"] == "ok"
        assert health["pid"] == os.getpid()
        assert health["uptime_s"] >= 0
        fr_event("tier", "warm", mkey=7, blocks=3)
        snap = json.loads(_get(srv.url("/flightrecorder")))
        tier = snap["planes"]["tier"]["events"]
        assert any(e[1] == "warm" and e[2]["mkey"] == 7 for e in tier)
    finally:
        RECORDER.release()
        srv.stop()
    # recorder off: the endpoint still answers, honestly
    srv2 = MetricsHttpServer(0)
    try:
        snap = json.loads(_get(srv2.url("/flightrecorder")))
        assert snap == {"enabled": False, "planes": {}}
    finally:
        srv2.stop()


# -- wire-version negotiation, both directions --------------------------------


def test_connector_downgrades_to_v1_acceptor():
    """A peer whose acceptor NAKs with ``srv_ver=1`` gets re-dialed at
    version 1; the channel pins the negotiated generation so v2-only
    bytes stay off the connection."""
    GLOBAL_REGISTRY.enabled = True
    port = BASE_PORT
    ready = threading.Event()
    hellos = []

    def v1_server():
        srv = socket.create_server(("127.0.0.1", port))
        srv.settimeout(10)
        ready.set()
        for _ in range(2):
            sock, _addr = srv.accept()
            hello = b""
            while len(hello) < wire._HELLO.size:
                hello += sock.recv(wire._HELLO.size - len(hello))
            _magic, _ct, _port, ver = wire._HELLO.unpack(hello)
            hellos.append(ver)
            if ver != 1:
                sock.sendall(b"\x00" + wire._HELLO_REJ.pack(1, ver))
                sock.close()
                continue
            sock.sendall(b"\x01")
            srv.close()
            return sock  # hold the accepted v1 channel open

    t = threading.Thread(target=v1_server, daemon=True)
    t.start()
    assert ready.wait(5)
    net = TcpNetwork()
    node = Node(("127.0.0.1", port + 1), TpuShuffleConf({
        "spark.shuffle.tpu.connectTimeout": "5s",
    }))
    base = counter(
        "wire_version_downgrades_total", transport="tcp"
    ).value
    try:
        ch = net.connect(node, ("127.0.0.1", port), ChannelType.RPC_REQUESTOR)
        assert ch.wire_version == 1
        assert hellos == [wire.WIRE_VERSION, 1]
        assert counter(
            "wire_version_downgrades_total", transport="tcp"
        ).value - base == 1
        ch.stop()
    finally:
        node.stop()
        t.join(timeout=10)


def test_listener_accepts_v1_hello():
    """The other direction: a v1 peer dialing THIS node's acceptor is
    admitted (MIN_WIRE_VERSION), not NAKed."""
    port = BASE_PORT + 10
    net = TcpNetwork()
    node = Node(("127.0.0.1", port), TpuShuffleConf({}))
    net.register(node)
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.settimeout(10)
        s.sendall(wire._HELLO.pack(
            wire._MAGIC,
            wire._TYPE_BY_INDEX.index(ChannelType.RPC_REQUESTOR),
            55321, 1,
        ))
        assert s.recv(1) == b"\x01"
        s.close()
    finally:
        node.stop()
        net.unregister(node)


def test_req_trace_tail_parses_and_requires_nonzero():
    base = wire._REQ_HDR.pack(7, 1) + wire._LOC.pack(0, 16, 1)
    assert wire._req_trace(base) is None
    tail = base + wire._TRACE_CTX.pack(0xAB, 0xCD)
    assert wire._req_trace(tail) == (0xAB, 0xCD)
    # zero trace id is "no trace" even if bytes are present
    zero = base + wire._TRACE_CTX.pack(0, 0xCD)
    assert wire._req_trace(zero) is None


# -- chaos auto-dump rendered by trace_report ---------------------------------


def _cluster(conf, n_execs=2):
    net = LoopbackNetwork()
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    execs = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=conf.driver_port + 100 + i * 10, executor_id=str(i),
        )
        for i in range(n_execs)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n_execs for e in execs):
            break
        time.sleep(0.01)
    return net, driver, execs


def test_chaos_fetch_failure_auto_dumps_and_report_names_fault(tmp_path):
    """The acceptance path end to end: a seeded serve fault exhausts
    the in-task retries, the terminal FetchFailed auto-dumps the
    flight recorder, and tools/trace_report.py renders that dump
    NAMING the injected fault point."""
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": BASE_PORT + 20,
        "spark.shuffle.tpu.metrics": True,
        "spark.shuffle.tpu.faultInject": "serve:p=1;seed=11",
        "spark.shuffle.tpu.fetchRetryCount": 1,
        "spark.shuffle.tpu.fetchRetryWaitMs": "10ms",
        "spark.shuffle.tpu.flightRecorderDumpPath": str(tmp_path),
    })
    net, driver, execs = _cluster(conf)
    try:
        handle = driver.register_shuffle(21, 2, HashPartitioner(2))
        maps_by_host = defaultdict(list)
        for m in range(2):
            w = execs[m].get_writer(handle, m)
            w.write([(j % 5, j) for j in range(100)])
            w.stop(True)
            maps_by_host[execs[m].local_smid].append(m)
        with pytest.raises(FetchFailedError):
            list(execs[0].get_reader(
                handle, 0, 1, dict(maps_by_host)
            ).read())
    finally:
        for m in execs + [driver]:
            m.stop()
    dumps = [
        os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
        if "fetch_failed" in f
    ]
    assert dumps, f"no fetch_failed auto-dump in {os.listdir(tmp_path)}"
    out = subprocess.run(
        [sys.executable, TRACE_REPORT, dumps[0]],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "injected fault points:" in out.stdout
    assert "serve" in out.stdout.split("injected fault points:")[1]
    assert "reader/fetch_fail" in out.stdout
    assert "faults/fault_fired" in out.stdout


# -- 2-process merged trace (simfleet) ----------------------------------------


def test_two_process_merged_trace_spans_requester_and_server(tmp_path):
    """SimPeerFleetProc serves from its OWN process; the requester's
    trace context rides the READ_REQ v2 tail, so the child's
    serve_read events carry the parent's trace id.  Merging the two
    per-process dumps yields ONE trace whose events span both pids."""
    pattern = (np.arange(1 << 16, dtype=np.uint32) % 251).astype(np.uint8)
    fleet_dump = str(tmp_path / "fleet.json")
    fleet = SimPeerFleetProc(
        1, BASE_PORT + 40, pattern.tobytes(), dump_path=fleet_dump,
    )
    RECORDER.retain(ring_size=4096)
    TRACING.retain(1.0)
    node = Node(("127.0.0.1", BASE_PORT + 50), TpuShuffleConf({}))
    ctx = TRACING.start()
    try:
        child = ctx.child()
        locs = [BlockLocation(64, 4096, 1), BlockLocation(8192, 1024, 1)]
        done = threading.Event()
        res = {}
        group = node.get_read_group(fleet.addresses[0], TcpNetwork().connect)
        group.read_blocks(
            locs,
            FnCompletionListener(
                lambda blocks: (res.setdefault("blocks", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
            ctx=child,
        )
        assert done.wait(30), "fleet read hung"
        assert "error" not in res, res.get("error")
        for loc, blk in zip(locs, res["blocks"]):
            got = np.frombuffer(memoryview(blk), np.uint8)
            assert np.array_equal(
                got, pattern[loc.address:loc.address + loc.length]
            )
    finally:
        node.stop()
        fleet.close()
    my_dump = str(tmp_path / "requester.json")
    assert write_dump(my_dump, reason="test") == my_dump
    TRACING.release()
    RECORDER.release()
    assert os.path.exists(fleet_dump), "child left no dump"

    doc = merge_dumps([my_dump, fleet_dump])
    events = [
        e for e in merged_events(doc)
        if e["fields"].get("trace_id") == ctx.trace_id
    ]
    pids = {e["pid"] for e in events}
    assert len(pids) == 2, (
        f"trace {ctx.trace_id:#x} does not span both processes: {events}"
    )
    names = {(e["plane"], e["name"]) for e in events}
    assert ("transport", "wire_send") in names     # requester side
    assert ("transport", "serve_read") in names    # server side
    server_pid = next(iter(pids - {os.getpid()}))
    assert any(
        e["pid"] == server_pid and e["name"] == "serve_read"
        for e in events
    )
    # and the renderer shows one merged waterfall for the trace
    out = subprocess.run(
        [sys.executable, TRACE_REPORT, my_dump, fleet_dump],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert f"trace 0x{ctx.trace_id:016x}" in out.stdout
    assert "2 process(es)" in out.stdout


# -- manager wiring -----------------------------------------------------------


def test_manager_retains_recorder_and_tracing_from_conf(tmp_path):
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": BASE_PORT + 60,
        "spark.shuffle.tpu.traceEnabled": True,
        "spark.shuffle.tpu.flightRecorderDumpPath": str(tmp_path),
    })
    mgr = TpuShuffleManager(
        conf, is_driver=True, network=LoopbackNetwork(),
    )
    try:
        assert RECORDER.enabled
        assert TRACING.enabled
    finally:
        mgr.stop()
    assert not RECORDER.enabled
    assert not TRACING.enabled
    # stop with a dump dir leaves a manager_stop snapshot
    assert any(
        "manager_stop" in f for f in os.listdir(tmp_path)
    ), os.listdir(tmp_path)


def test_trace_off_shuffle_has_no_trace_bytes_or_events():
    """traceEnabled default-off: the reader stamps nothing, fetch-status
    RPCs carry all-zero ids (v1-identical bytes, golden-pinned), and
    no trace-carrying event lands in the rings."""
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": BASE_PORT + 70,
        "spark.shuffle.tpu.flightRecorder": True,
    })
    net, driver, execs = _cluster(conf)
    try:
        assert RECORDER.enabled
        assert not TRACING.enabled
        handle = driver.register_shuffle(22, 2, HashPartitioner(2))
        maps_by_host = defaultdict(list)
        for m in range(2):
            w = execs[m].get_writer(handle, m)
            w.write([(j % 5, j) for j in range(100)])
            w.stop(True)
            maps_by_host[execs[m].local_smid].append(m)
        records = []
        for p in range(2):
            records.extend(execs[(p + 1) % 2].get_reader(
                handle, p, p + 1, dict(maps_by_host)
            ).read())
        assert len(records) == 200
        snap = RECORDER.snapshot()
        for plane, rec in snap["planes"].items():
            for _t, name, fields in rec["events"]:
                assert not fields.get("trace_id"), (
                    f"trace id leaked into {plane}/{name} with tracing off"
                )
        # the reader DID record its lifecycle, untraced
        reader_names = {
            e[1] for e in snap["planes"]["reader"]["events"]
        }
        assert "fetch_enqueue" in reader_names
        driver.unregister_shuffle(22)
    finally:
        for m in execs + [driver]:
            m.stop()
