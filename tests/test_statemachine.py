"""Runtime lifecycle state-machine validator + schedule shaker
(utils/statemachine.py, conf stateDebug / schedShake):

- with the conf OFF, ``_transition()`` is the plain assignment —
  structural identity plus a striped-fetch A/B microbench;
- with it ON, legal transitions count
  ``state_transitions_total{machine=,from=,to=}``, terminal entries
  count the terminal census, illegal edges raise
  :class:`IllegalTransition` with a 4-frame call site, and ``frm=``
  mismatches report expected-vs-seen;
- the schedule shaker replays a deterministic per-machine perturbation
  stream for a fixed seed;
- pinning regressions for the two ordering bugs the annotation sweep
  surfaced: the breaker's stale-success-in-OPEN window and
  ``manager.stop()``'s unguarded check-then-set."""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.faults.breaker import CircuitBreaker
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.utils.statemachine import (
    GLOBAL_STATE_DEBUG,
    IllegalTransition,
    StateMachine,
    check_named,
    get_state_debug,
    shake_confs_from_env,
    state_token,
)

BASE_PORT = 26400


@pytest.fixture()
def state_env():
    """Save/restore the process-global validator + metrics registry."""
    sd = get_state_debug()
    prev_enabled, prev_seed = sd.enabled, sd.shake_seed
    prev_reg = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    GLOBAL_REGISTRY.reset()
    sd.reset()
    yield sd
    sd.enabled, sd.shake_seed = prev_enabled, prev_seed
    sd.reset()
    GLOBAL_REGISTRY.enabled = prev_reg
    GLOBAL_REGISTRY.reset()


def _metric(name, **labels):
    for c in GLOBAL_REGISTRY.snapshot()["counters"]:
        if c["name"] == name and c["labels"] == labels:
            return c["value"]
    return 0


class Door(StateMachine):
    MACHINE = "test.door"
    STATES = ("open", "closing", "closed")
    INITIAL = "open"
    TERMINAL = ("closed",)
    TRANSITIONS = {
        "open": ("closing",),
        "closing": ("closed",),
    }

    def __init__(self):
        self._state = "open"  # state: test.door


# -- state_token --------------------------------------------------------------


def test_state_token_strings_pass_through():
    assert state_token("half-open") == "half-open"


def test_state_token_enum_members_lower_name():
    import enum

    class S(enum.Enum):
        IDLE = 0
        RESP_HDR = 7

    assert state_token(S.IDLE) == "idle"
    assert state_token(S.RESP_HDR) == "resp_hdr"


# -- disabled: plain assignment ----------------------------------------------


def test_disabled_transition_is_plain_assignment(state_env):
    state_env.enabled = False
    d = Door()
    d._transition("closed")  # illegal edge — nobody checks when off
    assert d._state == "closed"
    assert _metric("state_transitions_total", machine="test.door",
                   **{"from": "open", "to": "closed"}) == 0
    assert not state_env._rngs


# -- enabled: validation, counters, terminal census ---------------------------


def test_legal_walk_counts_transitions_and_terminal(state_env):
    state_env.enabled = True
    d = Door()
    d._transition("closing", frm="open")
    d._transition("closed", frm="closing")
    assert d._state == "closed"
    assert _metric("state_transitions_total", machine="test.door",
                   **{"from": "open", "to": "closing"}) == 1
    assert _metric("state_transitions_total", machine="test.door",
                   **{"from": "closing", "to": "closed"}) == 1
    assert _metric("state_terminal_total", machine="test.door",
                   state="closed") == 1


def test_illegal_edge_raises_with_site_chain(state_env):
    state_env.enabled = True
    d = Door()
    with pytest.raises(IllegalTransition) as ei:
        d._transition("closed")  # open -> closed not declared
    err = ei.value
    assert (err.machine, err.frm, err.to) == ("test.door", "open", "closed")
    # 4-frame site chain: file:line:function, joined by ' <- '
    assert "test_statemachine.py" in err.site
    assert err.site.count(" <- ") >= 1
    assert d._state == "open"  # the write never happened
    assert _metric("state_transitions_illegal_total",
                   machine="test.door") == 1


def test_frm_mismatch_reports_expected_vs_seen(state_env):
    state_env.enabled = True
    d = Door()
    with pytest.raises(IllegalTransition) as ei:
        d._transition("closing", frm="closing")
    assert "expected from='closing' saw 'open'" in str(ei.value)


def test_self_transition_is_silent_noop(state_env):
    state_env.enabled = True
    d = Door()
    d._transition("open")  # re-assert current state: legal, uncounted
    assert d._state == "open"
    assert _metric("state_transitions_total", machine="test.door",
                   **{"from": "open", "to": "open"}) == 0


def test_terminal_writes_raise(state_env):
    state_env.enabled = True
    d = Door()
    d._transition("closing")
    d._transition("closed")
    with pytest.raises(IllegalTransition):
        d._transition("open")  # terminal states declare no edges out


def test_check_named_secondary_table(state_env):
    state_env.enabled = True

    class Host:
        RX_TRANSITIONS = {"hdr": ("rpc",), "rpc": ("hdr",)}

        def __init__(self):
            self._rx_state = "hdr"

        def _transition_rx(self, state):
            if GLOBAL_STATE_DEBUG.enabled:
                check_named(self, state, name="test.rx", field="_rx_state",
                            transitions=self.RX_TRANSITIONS)
            self._rx_state = state

    h = Host()
    h._transition_rx("rpc")
    h._transition_rx("hdr")
    assert _metric("state_transitions_total", machine="test.rx",
                   **{"from": "hdr", "to": "rpc"}) == 1
    with pytest.raises(IllegalTransition):
        h._transition_rx("nonsense")


# -- the schedule shaker ------------------------------------------------------


def test_shaker_streams_are_deterministic_per_machine(state_env):
    state_env.enabled = True
    state_env.shake_seed = 20260807
    d = Door()
    d._transition("closing")
    d._transition("closed")
    draws_a = dict(state_env._rngs)
    assert "test.door" in draws_a
    # re-arm with the same seed: the stream replays bit-for-bit
    state_env.reset()
    d2 = Door()
    d2._transition("closing")
    d2._transition("closed")
    # same seed + same machine + same call count => same rng position
    a = draws_a["test.door"].random()
    b = state_env._rngs["test.door"].random()
    assert a == b


def test_shake_implies_state_debug_via_conf():
    conf = TpuShuffleConf({"spark.shuffle.tpu.schedShake": 123})
    assert conf.sched_shake == 123
    assert conf.state_debug  # shake without validation is meaningless
    off = TpuShuffleConf({})
    assert off.sched_shake == 0 and not off.state_debug


def test_shake_confs_from_env():
    assert shake_confs_from_env({}) == {}
    got = shake_confs_from_env({"SCHED_SHAKE": "7"})
    assert got["spark.shuffle.tpu.schedShake"] == "7"
    assert got["spark.shuffle.tpu.stateDebug"] is True


def test_manager_arms_global_validator_from_conf(state_env):
    state_env.enabled = False
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.stateDebug": True,
        "spark.shuffle.tpu.driverPort": BASE_PORT,
    })
    m = TpuShuffleManager(conf, is_driver=True, network=LoopbackNetwork())
    try:
        assert state_env.enabled
    finally:
        m.stop()


# -- pinning: the breaker probe window ----------------------------------------


def test_breaker_stale_success_does_not_close_open_breaker(state_env):
    """A success recorded while OPEN is a response to a fetch issued
    BEFORE the trip: closing on it would skip the half-open probe
    protocol off one straggler.  The sweep found record_success()
    doing exactly that; it must stay OPEN now."""
    state_env.enabled = True
    clk = [0.0]
    br = CircuitBreaker(failures=2, reset_ms=100, name="p",
                        clock=lambda: clk[0])
    br.record_failure()
    br.record_failure()
    assert br.state == "open"
    br.record_success()  # the straggler lands
    assert br.state == "open"  # NOT closed: probe is the only way back
    assert not br.allow()  # still refusing inside the reset window
    clk[0] = 0.2
    assert br.allow()  # the probe
    assert br.state == "half-open"
    br.record_success()
    assert br.state == "closed"


def test_breaker_probe_failure_reopens(state_env):
    state_env.enabled = True
    clk = [0.0]
    br = CircuitBreaker(failures=1, reset_ms=50, name="p",
                        clock=lambda: clk[0])
    br.record_failure()
    clk[0] = 0.1
    assert br.allow()
    br.record_failure()  # probe failed
    assert br.state == "open"
    assert not br.allow()  # clock restarted
    assert _metric("state_transitions_illegal_total",
                   machine="faults.breaker") == 0


# -- pinning: concurrent manager.stop() ---------------------------------------


def test_concurrent_manager_stop_single_teardown(state_env):
    """The sweep found stop()'s stopped-check was check-then-set
    without a lock: two racing stops could BOTH run teardown (double
    ledger flush, double node stop).  Under stateDebug a double
    teardown would now raise IllegalTransition (running->stopping
    twice); the _life_lock transition makes the loser return early."""
    state_env.enabled = True
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.stateDebug": True,
        "spark.shuffle.tpu.driverPort": BASE_PORT + 40,
    })
    m = TpuShuffleManager(conf, is_driver=True, network=LoopbackNetwork())
    errors = []
    gate = threading.Barrier(4)

    def stopper():
        try:
            gate.wait(5)
            m.stop()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=stopper) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
        assert not t.is_alive(), "stop() hung"
    assert not errors, errors
    assert m._state == "stopped"
    # exactly one winner made each lifecycle edge
    assert _metric("state_transitions_total", machine="manager.lifecycle",
                   **{"from": "running", "to": "stopping"}) == 1
    assert _metric("state_transitions_total", machine="manager.lifecycle",
                   **{"from": "stopping", "to": "stopped"}) == 1
    assert _metric("state_transitions_illegal_total",
                   machine="manager.lifecycle") == 0
    m.stop()  # idempotent afterwards


# -- identity: stateDebug=off on the striped-fetch microbench -----------------


def _striped_fetch_once(port):
    """One striped read pair on loopback (the test_striped_transport
    harness shape, shrunk): returns elapsed seconds for 12 reads."""
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.transport.node import Node
    from sparkrdma_tpu.utils.types import BlockLocation

    pattern = (np.arange(1 << 20, dtype=np.uint32) % 251).astype(np.uint8)
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
    })
    net = LoopbackNetwork()
    a = Node(("127.0.0.1", port), conf)
    b = Node(("127.0.0.1", port + 7), conf)
    net.register(a)
    net.register(b)
    arena = ArenaManager()
    seg = arena.register(pattern, zero_copy_ok=True)
    b.register_block_store(seg.mkey, arena)
    try:
        group = a.get_read_group(b.address, net.connect)
        t0 = time.perf_counter()
        for _ in range(12):
            done = threading.Event()
            res = {}
            group.read_blocks(
                [BlockLocation(0, len(pattern), seg.mkey)],
                FnCompletionListener(
                    lambda blocks: (res.setdefault("b", blocks),
                                    done.set()),
                    lambda e: (res.setdefault("e", e), done.set()),
                ),
            )
            assert done.wait(30), "striped read hung"
            assert "e" not in res, res.get("e")
        return time.perf_counter() - t0
    finally:
        a.stop()
        b.stop()
        net.unregister(a)
        net.unregister(b)


def test_identity_state_debug_off_striped_fetch(state_env):
    """stateDebug=off must not tax the striped fetch path: B (the
    _transition helper, debug off) vs A (raw assignment, the pre-gate
    baseline reconstructed by patching the mixin) at >= 0.95x."""
    state_env.enabled = False
    raw = StateMachine._transition

    def plain(self, to, frm=None):
        setattr(self, self.STATE_FIELD, to)

    try:
        # interleave A/B pairs, keep the best of each: one warmup pair
        # absorbs import/JIT costs, min-of-3 absorbs scheduler noise
        a_times, b_times = [], []
        _striped_fetch_once(BASE_PORT + 60)
        for i in range(3):
            StateMachine._transition = plain
            a_times.append(_striped_fetch_once(BASE_PORT + 80 + i * 20))
            StateMachine._transition = raw
            b_times.append(_striped_fetch_once(BASE_PORT + 160 + i * 20))
    finally:
        StateMachine._transition = raw
    a, b = min(a_times), min(b_times)
    assert b <= a / 0.95 + 0.05, (
        f"stateDebug=off striped fetch {b:.4f}s vs raw-assignment "
        f"baseline {a:.4f}s — more than 5% overhead"
    )


# -- metrics_report: the state-machines table ---------------------------------


def test_metrics_report_state_machine_table():
    import importlib.util
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_metrics_report", repo / "tools" / "metrics_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    counters = [
        {"name": "state_transitions_total",
         "labels": {"machine": "m.x", "from": "a", "to": "b"}, "value": 3},
        {"name": "state_terminal_total",
         "labels": {"machine": "m.x", "state": "b"}, "value": 1},
        {"name": "state_transitions_illegal_total",
         "labels": {"machine": "m.y"}, "value": 2},
        {"name": "unrelated_total", "labels": {}, "value": 9},
    ]
    lines = mod.render_state_machines(counters)
    joined = "\n".join(lines)
    assert lines[0].startswith("state machines")
    assert "m.x" in joined and "top=a->b (3)" in joined
    assert "terminal: b=1" in joined
    assert "ILLEGAL=2" in joined
    assert mod.render_state_machines([]) == []


# -- property-based transition fuzz (hypothesis, optional dev dep) ------------


try:
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # optional dev dep (pyproject [dev]); not in the image
    HAVE_HYPOTHESIS = False

    def given(**kw):  # pragma: no cover - placeholder decorators
        return lambda fn: fn

    def settings(**kw):  # pragma: no cover
        return lambda fn: fn

    class st:  # pragma: no cover - strategy args evaluate at import
        lists = staticmethod(lambda *a, **kw: None)
        sampled_from = staticmethod(lambda *a, **kw: None)

needs_hypothesis = pytest.mark.skipif(
    not HAVE_HYPOTHESIS, reason="hypothesis not installed (optional dev dep)")


def _walk_machine(states, transitions, steps):
    """Drive a Door-like object through a random token walk; every
    step must either be a declared edge (mutates) or raise without
    mutating.  Returns the number of accepted steps."""

    class M(StateMachine):
        MACHINE = "fuzz.m"
        STATES = tuple(states)
        INITIAL = states[0]
        TERMINAL = ()
        TRANSITIONS = transitions

        def __init__(self):
            self._state = states[0]  # state: fuzz.m

    m = M()
    accepted = 0
    for to in steps:
        cur = m._state
        legal = to == cur or to in transitions.get(cur, ())
        if legal:
            m._transition(to)
            assert m._state == to
            accepted += 1
        else:
            with pytest.raises(IllegalTransition):
                m._transition(to)
            assert m._state == cur  # a refused edge never mutates
    return accepted


@needs_hypothesis
@settings(max_examples=60, deadline=None)
@given(steps=st.lists(
    st.sampled_from(["closed", "open", "half-open", "bogus"]),
    max_size=40))
def test_fuzz_breaker_table_walk(steps):
    sd = get_state_debug()
    prev = sd.enabled
    sd.enabled = True
    try:
        _walk_machine(
            ["closed", "open", "half-open"],
            dict(CircuitBreaker.TRANSITIONS), steps)
    finally:
        sd.enabled = prev


@needs_hypothesis
@settings(max_examples=60, deadline=None)
@given(steps=st.lists(
    st.sampled_from(["accepting", "sealed", "committed"]), max_size=40))
def test_fuzz_push_merge_table_walk(steps):
    from sparkrdma_tpu.shuffle.push import _ReduceMerge

    sd = get_state_debug()
    prev = sd.enabled
    sd.enabled = True
    try:
        _walk_machine(
            ["accepting", "sealed", "committed"],
            dict(_ReduceMerge.TRANSITIONS), steps)
    finally:
        sd.enabled = prev


@needs_hypothesis
@settings(max_examples=40, deadline=None)
@given(steps=st.lists(st.sampled_from(["open", "closed"]), max_size=20))
def test_fuzz_decode_stream_table_walk(steps):
    from sparkrdma_tpu.shuffle.decode import DecodeStream

    sd = get_state_debug()
    prev = sd.enabled
    sd.enabled = True
    try:
        _walk_machine(["open", "closed"],
                      dict(DecodeStream.TRANSITIONS), steps)
    finally:
        sd.enabled = prev
