"""Interpret-mode semantics for the experimental Pallas bitonic sort.

Exactness only — nothing dispatches to this kernel by default (see
ops/sort_kernel.py: on-chip profiling gates adoption)."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.ops.sort_kernel import (
    LANES,
    sort_pairs_blocks,
    sort_pairs_full,
)


def _pairs(n, seed, lo=None, hi=None, dtype=np.int32):
    rng = np.random.default_rng(seed)
    lo = -(1 << 30) if lo is None else lo
    hi = (1 << 30) if hi is None else hi
    k = rng.integers(lo, hi, n, dtype=dtype)
    v = np.arange(n, dtype=np.int32)  # unique: checks pairs move together
    return k, v


@pytest.mark.parametrize("block_rows", [8, 32])
def test_block_sort_each_block_sorted(block_rows):
    B = block_rows * LANES
    n = 4 * B
    k, v = _pairs(n, 1)
    ok, ov = sort_pairs_blocks(
        jnp.asarray(k), jnp.asarray(v), block_rows=block_rows,
        interpret=True,
    )
    ok = np.asarray(ok).reshape(4, B)
    ov = np.asarray(ov).reshape(4, B)
    for b in range(4):
        want_k = np.sort(k.reshape(4, B)[b])
        np.testing.assert_array_equal(ok[b], want_k)
        # pairs stayed together: v carries the original index
        np.testing.assert_array_equal(k[ov[b]], ok[b])


def test_block_sort_duplicate_and_extreme_keys():
    block_rows = 8
    B = block_rows * LANES
    rng = np.random.default_rng(2)
    k = rng.integers(0, 7, B, dtype=np.int32)  # heavy duplicates
    k[:4] = [np.iinfo(np.int32).max, np.iinfo(np.int32).min, 0, -1]
    v = np.arange(B, dtype=np.int32)
    ok, ov = sort_pairs_blocks(
        jnp.asarray(k), jnp.asarray(v), block_rows=block_rows,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.sort(k))
    np.testing.assert_array_equal(k[np.asarray(ov)], np.asarray(ok))


@pytest.mark.parametrize("seed,n_buckets", [(3, 4), (4, 16)])
def test_full_sort_matches_numpy(seed, n_buckets):
    block_rows = 8
    B = block_rows * LANES
    n = 16 * B
    k, v = _pairs(n, seed)
    ok, ov, valid, fn, overflow = sort_pairs_full(
        jnp.asarray(k), jnp.asarray(v), block_rows=block_rows,
        n_buckets=n_buckets, interpret=True,
    )
    assert int(overflow) <= np.asarray(ok).shape[0] // n_buckets
    ok = np.asarray(ok)
    ov = np.asarray(ov)
    m = np.asarray(valid) > 0
    assert m.sum() == n
    np.testing.assert_array_equal(ok[m], np.sort(k))
    np.testing.assert_array_equal(k[ov[m]], ok[m])


def test_full_sort_skewed_keys():
    block_rows = 8
    B = block_rows * LANES
    n = 8 * B
    rng = np.random.default_rng(9)
    k = np.where(
        rng.random(n) < 0.7, np.int32(42),
        rng.integers(0, 1000, n, dtype=np.int32),
    )
    v = np.arange(n, dtype=np.int32)
    ok, ov, valid, fn, overflow = sort_pairs_full(
        jnp.asarray(k), jnp.asarray(v), block_rows=block_rows,
        n_buckets=4, cap_factor=2.0, interpret=True,
    )
    cap = np.asarray(ok).shape[0] // 4
    if int(overflow) <= cap:  # no overflow at this factor
        m = np.asarray(valid) > 0
        np.testing.assert_array_equal(np.asarray(ok)[m], np.sort(k))


@pytest.mark.parametrize("pattern", ["sorted", "reverse", "constant"])
def test_full_sort_adversarial_patterns(pattern):
    """Pre-sorted, reverse-sorted, and all-equal inputs (the splitter
    sampling's worst cases) must stay exact — bench.py may adopt this
    engine unattended on hardware."""
    block_rows = 8
    B = block_rows * LANES
    n = 8 * B
    if pattern == "sorted":
        k = np.arange(n, dtype=np.int32)
    elif pattern == "reverse":
        k = np.arange(n, 0, -1).astype(np.int32)
    else:
        k = np.full(n, 7, np.int32)
    v = np.arange(n, dtype=np.int32)
    ok, ov, valid, fn, overflow = sort_pairs_full(
        jnp.asarray(k), jnp.asarray(v), block_rows=block_rows,
        n_buckets=4, cap_factor=2.0, interpret=True,
    )
    cap = np.asarray(ok).shape[0] // 4
    if int(overflow) > cap:
        return  # caller-visible overflow: retry path, not silent error
    m = np.asarray(valid) > 0
    assert m.sum() == n
    np.testing.assert_array_equal(np.asarray(ok)[m], np.sort(k))
    np.testing.assert_array_equal(k[np.asarray(ov)[m]], np.asarray(ok)[m])
