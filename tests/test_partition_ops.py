"""On-device partitioning ops (SURVEY.md §7: map-side as XLA programs)."""

import jax
import jax.numpy as jnp
import numpy as np

from sparkrdma_tpu.ops import (
    hash_partition_ids,
    make_range_splitters,
    partition_to_buckets,
    range_partition_ids,
)


def test_hash_partition_spread_and_determinism():
    keys = jnp.arange(10000, dtype=jnp.int32)
    ids = hash_partition_ids(keys, 8)
    assert int(ids.min()) >= 0 and int(ids.max()) < 8
    counts = np.bincount(np.asarray(ids), minlength=8)
    # avalanche: consecutive keys spread near-uniformly
    assert counts.min() > 10000 / 8 * 0.8
    ids2 = hash_partition_ids(keys, 8)
    np.testing.assert_array_equal(np.asarray(ids), np.asarray(ids2))


def test_range_splitters_and_ids():
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.integers(0, 1 << 30, size=4096, dtype=np.int64))
    spl = make_range_splitters(sample, 8)
    assert spl.shape == (7,)
    assert bool(jnp.all(spl[1:] >= spl[:-1]))
    keys = jnp.asarray(rng.integers(0, 1 << 30, size=10000, dtype=np.int64))
    ids = range_partition_ids(keys, spl)
    # each key's bucket respects splitter ordering
    np_keys, np_spl, np_ids = map(np.asarray, (keys, spl, ids))
    expect = np.searchsorted(np_spl, np_keys, side="right")
    np.testing.assert_array_equal(np_ids, expect)
    counts = np.bincount(np_ids, minlength=8)
    assert counts.min() > 10000 / 8 * 0.5  # roughly balanced


def test_partition_to_buckets_roundtrip():
    rng = np.random.default_rng(1)
    n, n_parts, cap = 1000, 8, 256
    keys = jnp.asarray(rng.integers(0, 1 << 20, size=n, dtype=np.int32))
    vals = jnp.asarray(rng.integers(0, 100, size=n, dtype=np.int32))
    ids = hash_partition_ids(keys, n_parts)
    (bk, bv), counts = partition_to_buckets(ids, (keys, vals), n_parts, cap)
    assert bk.shape == (n_parts, cap) and bv.shape == (n_parts, cap)
    np_ids = np.asarray(ids)
    np.testing.assert_array_equal(
        np.asarray(counts), np.bincount(np_ids, minlength=n_parts)
    )
    # every (key, val) pair lands in its bucket, pairs stay aligned
    np_k, np_v = np.asarray(keys), np.asarray(vals)
    for p in range(n_parts):
        c = int(counts[p])
        got_k = np.asarray(bk[p][:c])
        got_v = np.asarray(bv[p][:c])
        exp_k = np_k[np_ids == p]
        exp_v = np_v[np_ids == p]
        # within-bucket order is NOT guaranteed (unstable grouping sort,
        # matching Spark shuffle semantics) — but (key, val) pairs must
        # stay aligned: compare as multisets of pairs
        got = sorted(zip(got_k.tolist(), got_v.tolist()))
        exp = sorted(zip(exp_k.tolist(), exp_v.tolist()))
        assert got == exp
    # padding sorts last
    assert int(bk[0][-1]) == np.iinfo(np.int32).max or int(counts[0]) == cap


def test_partition_overflow_detected_not_corrupted():
    ids = jnp.zeros(100, dtype=jnp.int32)  # all to bucket 0
    keys = jnp.arange(100, dtype=jnp.int32)
    (bk,), counts = partition_to_buckets(ids, (keys,), 4, capacity=32)
    assert int(counts[0]) == 100  # true count signals overflow
    # capacity elements kept, all real and distinct (WHICH ones is
    # unspecified under the unstable grouping sort — the caller retries
    # with a larger capacity on overflow and discards this result)
    kept = np.asarray(bk[0])
    assert len(np.unique(kept)) == 32 and kept.min() >= 0 and kept.max() < 100
    # other buckets untouched (all padding)
    assert int(np.asarray(bk[1]).min()) == np.iinfo(np.int32).max


def test_partition_ops_are_jittable():
    @jax.jit
    def pipeline(keys):
        ids = hash_partition_ids(keys, 4)
        (bk,), counts = partition_to_buckets(ids, (keys,), 4, 64)
        return bk, counts

    keys = jnp.arange(100, dtype=jnp.int32)
    bk, counts = pipeline(keys)
    assert bk.shape == (4, 64)
    assert int(counts.sum()) == 100


def test_partition_multidim_values():
    # reviewer finding: [n, d] value arrays must bucket alongside keys
    rng = np.random.default_rng(3)
    n = 500
    keys = jnp.asarray(rng.integers(0, 1 << 20, size=n, dtype=np.int32))
    emb = jnp.asarray(rng.integers(0, 100, size=(n, 4), dtype=np.int32))
    ids = hash_partition_ids(keys, 4)
    (bk, be), counts = partition_to_buckets(ids, (keys, emb), 4, 256)
    assert be.shape == (4, 256, 4)
    np_ids = np.asarray(ids)
    for p in range(4):
        c = int(counts[p])
        # rows must travel with their keys (order within bucket is
        # unspecified): compare (key, row) pairs as sorted tuples
        got = sorted(
            (int(k), tuple(r))
            for k, r in zip(np.asarray(bk[p][:c]), np.asarray(be[p][:c]))
        )
        exp = sorted(
            (int(k), tuple(r))
            for k, r in zip(
                np.asarray(keys)[np_ids == p], np.asarray(emb)[np_ids == p]
            )
        )
        assert got == exp


def test_partition_empty_input():
    # reviewer finding: empty local shards must produce all-fill buckets
    (bk,), counts = partition_to_buckets(
        jnp.zeros((0,), jnp.int32), (jnp.zeros((0,), jnp.int32),), 4, 8
    )
    assert bk.shape == (4, 8)
    assert int(np.asarray(counts).sum()) == 0
    assert int(np.asarray(bk).min()) == np.iinfo(np.int32).max
