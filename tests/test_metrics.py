"""Metrics registry unit tests + end-to-end instrumentation: a real
loopback shuffle with conf ``metrics`` on must show nonzero transport
bytes, writer bytes, fetch-latency histogram counts and arena
allocation counts in the snapshot, the driver must aggregate the
per-shuffle telemetry, and tools/metrics_report.py must render it
(ISSUE 1 acceptance)."""

import json
import subprocess
import sys
import threading
import time
from collections import defaultdict
from pathlib import Path

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import (
    GLOBAL_REGISTRY,
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    MetricsRegistry,
    diff_snapshots,
    to_prometheus,
)
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def registry():
    """Fresh, enabled GLOBAL registry; state restored afterwards."""
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    yield GLOBAL_REGISTRY
    GLOBAL_REGISTRY.enabled = prev
    GLOBAL_REGISTRY.reset()


# -- unit: instruments ------------------------------------------------------


def test_counter_concurrent_increments():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("c_total")

    def work():
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 80_000


def test_instrument_identity_and_labels():
    reg = MetricsRegistry(enabled=True)
    a = reg.counter("x_total", transport="tcp")
    b = reg.counter("x_total", transport="tcp")
    c = reg.counter("x_total", transport="loopback")
    assert a is b
    assert a is not c
    a.inc(2)
    snap = reg.snapshot()
    vals = {
        (r["name"], tuple(sorted(r["labels"].items()))): r["value"]
        for r in snap["counters"]
    }
    assert vals[("x_total", (("transport", "tcp"),))] == 2
    assert vals[("x_total", (("transport", "loopback"),))] == 0


def test_disabled_registry_returns_noop_handles():
    reg = MetricsRegistry(enabled=False)
    assert reg.counter("a") is NULL_COUNTER
    assert reg.gauge("b") is NULL_GAUGE
    assert reg.histogram("c") is NULL_HISTOGRAM
    reg.counter("a").inc(5)          # must be a no-op
    reg.histogram("c").observe(1.0)  # must be a no-op
    with reg.histogram("c").time():
        pass
    assert reg.snapshot()["counters"] == []
    # force=True bypasses the gate (used by the conf-gated reader stats)
    real = reg.counter("a", force=True)
    real.inc(5)
    assert real.value == 5


def test_histogram_edges_are_exclusive_upper_bounds():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("h_ms", edges=[1.0, 10.0])
    for v in (0.0, 0.99, 1.0, 9.99, 10.0, 1e9):
        h.observe(v)
    assert h.counts == [2, 2, 2]
    assert h.count == 6
    assert h.sum == pytest.approx(sum((0.0, 0.99, 1.0, 9.99, 10.0, 1e9)))


def test_histogram_time_context():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("t_ms")
    with h.time():
        time.sleep(0.002)
    assert h.count == 1
    assert h.sum >= 1.0  # at least ~2ms observed


def test_gauge_inc_dec():
    reg = MetricsRegistry(enabled=True)
    g = reg.gauge("g")
    g.inc(3)
    g.dec()
    assert g.value == 2
    g.set(7.5)
    assert g.value == 7.5


# -- unit: exposition / diff ------------------------------------------------


def test_prometheus_exposition_shape():
    reg = MetricsRegistry(enabled=True)
    reg.counter("n_total", layer="t").inc(4)
    reg.gauge("active").set(2)
    h = reg.histogram("lat_ms", edges=[1.0, 5.0])
    h.observe(0.5)
    h.observe(3.0)
    h.observe(100.0)
    text = to_prometheus(reg)
    assert "# TYPE n_total counter" in text
    assert 'n_total{layer="t"} 4' in text
    assert "# TYPE active gauge" in text
    assert "# TYPE lat_ms histogram" in text
    assert 'lat_ms_bucket{le="1"} 1' in text
    assert 'lat_ms_bucket{le="5"} 2' in text
    assert 'lat_ms_bucket{le="+Inf"} 3' in text
    assert "lat_ms_count 3" in text


def test_diff_snapshots_subtracts_counters_and_histograms():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("c_total")
    h = reg.histogram("h_ms", edges=[1.0])
    c.inc(5)
    h.observe(0.5)
    base = reg.snapshot()
    c.inc(3)
    h.observe(2.0)
    d = diff_snapshots(reg.snapshot(), base)
    assert d["counters"][0]["value"] == 3
    assert d["histograms"][0]["counts"] == [0, 1]
    assert d["histograms"][0]["count"] == 1


def test_publish_to_tracer_bridges_counters():
    from sparkrdma_tpu.utils.trace import Tracer

    reg = MetricsRegistry(enabled=True)
    reg.counter("br_total", k="v").inc(9)
    reg.gauge("br_gauge").set(4)
    tr = Tracer(enabled=True)
    reg.publish_to_tracer(tr)
    events = {e["name"]: e for e in tr.events}
    assert events["br_total{k=v}"]["args"]["value"] == 9
    assert events["br_gauge"]["args"]["value"] == 4
    assert all(e["ph"] == "C" for e in tr.events)


# -- end to end -------------------------------------------------------------


def _sum_counter(snap, name):
    return sum(
        c["value"] for c in snap["counters"] if c["name"] == name
    )


def test_e2e_shuffle_metrics(registry, tmp_path):
    net = LoopbackNetwork()
    json_path = tmp_path / "metrics.json"
    prom_path = tmp_path / "metrics.prom"
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.metrics": True,
        "spark.shuffle.tpu.collectShuffleReaderStats": True,
        "spark.shuffle.tpu.driverPort": 37310,
        "spark.shuffle.tpu.metricsJsonPath": str(json_path),
        "spark.shuffle.tpu.metricsPromPath": str(prom_path),
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=38310 + i * 10, executor_id=str(i),
        )
        for i in range(3)
    ]
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == 3 for e in executors):
                break
            time.sleep(0.01)

        num_maps, num_parts = 4, 6
        handle = driver.register_shuffle(
            0, num_maps, HashPartitioner(num_parts)
        )
        maps_by_host = defaultdict(list)
        for map_id in range(num_maps):
            ex = executors[map_id % 3]
            w = ex.get_writer(handle, map_id)
            w.write([(f"k{j}", (map_id, j)) for j in range(100)])
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)
        maps_by_host = dict(maps_by_host)

        got = 0
        for pid in range(num_parts):
            ex = executors[pid % 3]
            reader = ex.get_reader(handle, pid, pid + 1, maps_by_host)
            got += sum(1 for _ in reader.read())
        assert got == num_maps * 100

        driver.unregister_shuffle(0)
        for ex in executors:
            ex.unregister_shuffle(0)

        # telemetry publishes ride the async control plane
        deadline = time.monotonic() + 5
        tel = {}
        while time.monotonic() < deadline:
            tel = driver.shuffle_telemetry(0)
            if tel["total"].get("map_tasks", 0) >= num_maps and \
                    tel["total"].get("reduce_tasks", 0) >= num_parts:
                break
            time.sleep(0.01)
        assert tel["total"]["map_tasks"] == num_maps
        assert tel["total"]["reduce_tasks"] == num_parts
        assert tel["total"]["write_bytes"] > 0
        assert tel["total"]["write_records"] == num_maps * 100
        assert tel["total"]["records_read"] == num_maps * 100
        assert len(tel["per_host"]) == 3

        snap = registry.snapshot()
        # ISSUE 1 acceptance: nonzero transport bytes, writer bytes,
        # fetch-latency histogram counts, arena allocation counts
        assert _sum_counter(snap, "transport_bytes_sent_total") > 0
        assert _sum_counter(snap, "shuffle_write_bytes_total") > 0
        assert _sum_counter(snap, "arena_segments_registered_total") > 0
        fetch = [
            h for h in snap["histograms"]
            if h["name"] in ("shuffle_fetch_latency_ms",
                             "shuffle_remote_fetch_ms")
        ]
        assert sum(h["count"] for h in fetch) > 0
        assert _sum_counter(snap, "shuffle_read_bytes_total") > 0
        assert _sum_counter(snap, "transport_connect_attempts_total") > 0
    finally:
        for m in executors + [driver]:
            m.stop()

    # stop-time exports: driver writes the bare paths, executors suffix
    assert json_path.exists()
    assert prom_path.exists()
    assert (tmp_path / "metrics.json.0").exists()
    doc = json.loads(json_path.read_text())
    assert _sum_counter(doc, "shuffle_write_bytes_total") > 0
    assert "transport_bytes_sent_total" in prom_path.read_text()

    # the CLI renders the snapshot (and a self-diff) without error
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "metrics_report.py"),
         str(json_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "shuffle_write_bytes_total" in out.stdout
    assert "histograms" in out.stdout
    out2 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "metrics_report.py"),
         str(json_path), str(json_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert out2.returncode == 0, out2.stderr
    assert "diff" in out2.stdout


def test_metrics_disabled_leaves_registry_empty(tmp_path):
    """Default conf: the instrumented paths must not create instruments
    (no-op handles) — the zero-overhead contract."""
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = False
    GLOBAL_REGISTRY.reset()
    try:
        net = LoopbackNetwork()
        conf = TpuShuffleConf({
            "spark.shuffle.tpu.driverPort": 37350,
        })
        driver = TpuShuffleManager(conf, is_driver=True, network=net)
        ex = TpuShuffleManager(
            conf, is_driver=False, network=net, port=38350,
            executor_id="0",
        )
        try:
            handle = driver.register_shuffle(0, 1, HashPartitioner(2))
            w = ex.get_writer(handle, 0)
            w.write([(1, 2), (3, 4)])
            w.stop(True)
            reader = ex.get_reader(
                handle, 0, 1, {ex.local_smid: [0]}
            )
            list(reader.read())
            driver.unregister_shuffle(0)
            ex.unregister_shuffle(0)
        finally:
            ex.stop()
            driver.stop()
        snap = GLOBAL_REGISTRY.snapshot()
        assert snap["counters"] == []
        assert snap["gauges"] == []
        # no per-shuffle telemetry accumulates either
        assert driver.shuffle_telemetry(0)["per_host"] == {}
    finally:
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()


def test_prometheus_parse_round_trips_with_snapshot_render():
    """A live Prometheus scrape must render (tools/metrics_report.py)
    exactly like the stop-time JSON snapshot of the same registry:
    snapshot → exposition text → parse_prometheus → render is the
    identity on the rendered report, counters/gauges/histograms and
    the resource-census series included."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_metrics_report",
        REPO / "tools" / "metrics_report.py",
    )
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)

    reg = MetricsRegistry(enabled=True)
    reg.counter("shuffle_write_bytes_total").inc(123456)
    reg.counter("resource_acquires_total", resource="x.pins").inc(3)
    reg.counter("resource_leaked_total", resource="x.pins").inc(1)
    reg.counter("resource_double_release_total").inc(2)
    reg.gauge("resource_outstanding", resource="x.pins").set(2)
    reg.gauge("arena_bytes_in_use").set(4096)
    h = reg.histogram("fetch_ms", edges=[1.0, 5.0, 25.0])
    for v in (0.5, 3.0, 3.0, 17.0, 99.0):
        h.observe(v)
    hl = reg.histogram("lock_hold_us", edges=[10.0, 100.0], lock="arena")
    for v in (4.0, 40.0, 400.0):
        hl.observe(v)

    snap = reg.snapshot()
    parsed = report.parse_prometheus(to_prometheus(reg))
    assert report.render(parsed) == report.render(snap)

    # the parse reconstructed the exact series, not just the rendering
    assert parsed["counters"] == snap["counters"]
    assert parsed["gauges"] == snap["gauges"]
    assert len(parsed["histograms"]) == len(snap["histograms"])
    by_key = {
        (h["name"], tuple(sorted((h.get("labels") or {}).items()))): h
        for h in parsed["histograms"]
    }
    for want in snap["histograms"]:
        got = by_key[
            (want["name"],
             tuple(sorted((want.get("labels") or {}).items())))
        ]
        assert got["edges"] == list(want["edges"])
        assert got["counts"] == list(want["counts"])
        assert got["count"] == want["count"]
        assert got["sum"] == pytest.approx(want["sum"])
