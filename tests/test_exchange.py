"""Exchange engine + partition ops on the 8-device CPU mesh
(SURVEY.md §7 step 4: ICI exchange engine)."""

import math

import numpy as np
import pytest

from sparkrdma_tpu.parallel import ExchangePlan, TileExchange, make_mesh


@pytest.fixture(scope="module")
def mesh(request):
    return make_mesh(8)


def make_streams(rng, D, max_len=5000):
    return [
        [
            rng.integers(0, 256, size=int(rng.integers(0, max_len)), dtype=np.uint8)
            .tobytes()
            for _ in range(D)
        ]
        for _ in range(D)
    ]


def test_plan_tiles_and_rounds():
    lengths = np.array([[0, 1000], [70000, 5]])
    plan = ExchangePlan(lengths, tile_bytes=16384)
    assert plan.tile_bytes == 16384
    assert plan.rounds == math.ceil(70000 / 16384) == 5
    assert plan.payload_bytes == 71005
    # tile is lane-aligned even for tiny exchanges
    tiny = ExchangePlan(np.array([[3]]), tile_bytes=1 << 20)
    assert tiny.tile_bytes == 128 and tiny.rounds == 1


def test_plan_empty_exchange():
    plan = ExchangePlan(np.zeros((4, 4), dtype=np.int64), 1 << 20)
    assert plan.rounds == 0 and plan.total_cols == 0


def test_plan_validation():
    with pytest.raises(ValueError):
        ExchangePlan(np.zeros((2, 3)), 1024)
    with pytest.raises(ValueError):
        ExchangePlan(np.array([[-1, 0], [0, 0]]), 1024)


def test_exchange_single_round(mesh, devices):
    ex = TileExchange(mesh, tile_bytes=1 << 20)
    D = ex.n_devices
    rng = np.random.default_rng(0)
    streams = make_streams(rng, D)
    out = ex.exchange_bytes(streams)
    for s in range(D):
        for d in range(D):
            assert out[d][s] == streams[s][d], (s, d)


def test_exchange_multi_round_pipelined(mesh, devices):
    # small tiles force many rounds through the bounded in-flight window
    ex = TileExchange(mesh, tile_bytes=512, max_rounds_in_flight=3)
    D = ex.n_devices
    rng = np.random.default_rng(1)
    streams = make_streams(rng, D, max_len=20000)
    out = ex.exchange_bytes(streams)
    for s in range(D):
        for d in range(D):
            assert out[d][s] == streams[s][d], (s, d)
    assert ex.rounds_executed > 3  # really was multi-round
    st = ex.stats()
    assert st["payload_bytes_moved"] > 0
    assert st["padded_bytes_moved"] >= st["payload_bytes_moved"]


def test_exchange_skewed_and_empty_pairs(mesh, devices):
    ex = TileExchange(mesh, tile_bytes=1024)
    D = ex.n_devices
    streams = [[b"" for _ in range(D)] for _ in range(D)]
    streams[0][7] = bytes(range(256)) * 100  # one huge pair
    streams[3][3] = b"self-loop"             # local traffic
    out = ex.exchange_bytes(streams)
    assert out[7][0] == streams[0][7]
    assert out[3][3] == b"self-loop"
    assert out[1][2] == b""


def test_exchange_all_empty(mesh, devices):
    ex = TileExchange(mesh)
    D = ex.n_devices
    out = ex.exchange_bytes([[b""] * D] * D)
    assert all(out[d][s] == b"" for d in range(D) for s in range(D))
    assert ex.rounds_executed == 0


def test_exchange_shape_validation(mesh, devices):
    ex = TileExchange(mesh)
    with pytest.raises(ValueError):
        ex.exchange_bytes([[b""]])


def test_a2a_device_resident(mesh, devices):
    import jax.numpy as jnp

    ex = TileExchange(mesh)
    D = ex.n_devices
    rng = np.random.default_rng(2)
    x = rng.integers(0, 256, size=(D, D, 256), dtype=np.uint8)
    y = np.asarray(ex.a2a(jnp.asarray(x)))
    np.testing.assert_array_equal(y, x.swapaxes(0, 1))


def test_exchange_integrity_ok_and_stats(mesh, devices):
    from sparkrdma_tpu.parallel.exchange import TileExchange

    ex = TileExchange(mesh, tile_bytes=512, verify_integrity=True)
    D = ex.n_devices
    rng = np.random.default_rng(8)
    streams = [
        [rng.bytes(rng.integers(0, 2000)) for _ in range(D)]
        for _ in range(D)
    ]
    out = ex.exchange_bytes(streams)
    for d in range(D):
        for s in range(D):
            assert out[d][s] == streams[s][d]
    assert ex.stats()["integrity_failures"] == 0


def test_exchange_integrity_detects_corruption(mesh, devices):
    from sparkrdma_tpu.parallel.exchange import (
        ExchangeIntegrityError,
        TileExchange,
    )

    ex = TileExchange(mesh, tile_bytes=256, verify_integrity=True)
    D = ex.n_devices
    streams = [[bytes([s * D + d]) * 100 for d in range(D)] for s in range(D)]
    # what a healthy exchange delivers, then flip one byte in one stream
    received = [[bytearray(streams[s][d]) for s in range(D)] for d in range(D)]
    received[2][1][50] ^= 0xFF
    corrupted = [[bytes(b) for b in row] for row in received]
    with pytest.raises(ExchangeIntegrityError) as ei:
        ex._verify(streams, corrupted, set(range(D)))
    assert ex.stats()["integrity_failures"] == 1
    assert "1->2" in str(ei.value) and "crc32" in str(ei.value)
    assert ei.value.src == 1 and ei.value.dst == 2


def test_exchange_from_conf(mesh, devices):
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.parallel.exchange import TileExchange

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.exchangeTileBytes": "128k",
        "spark.shuffle.tpu.exchangeMaxRoundsInFlight": "4",
        "spark.shuffle.tpu.verifyExchangeIntegrity": "true",
    })
    ex = TileExchange.from_conf(conf, mesh)
    assert ex.tile_bytes == 128 << 10
    assert ex.max_rounds_in_flight == 4
    assert ex.verify_integrity is True
    # and the conf default leaves verification off (opt-in knob)
    ex2 = TileExchange.from_conf(TpuShuffleConf(), mesh)
    assert ex2.verify_integrity is False


def test_host_local_streams_guard():
    """Multi-host exchange results must fail loudly on remote rows
    (VERDICT round-1 weak #4: silently-empty streams)."""
    import pytest

    from sparkrdma_tpu.parallel.exchange import (
        HostLocalStreams,
        NonAddressableStreamError,
    )

    rows = [[b"aa", b"bb"], [b"cc", b"dd"]]
    res = HostLocalStreams(rows, frozenset({1}))
    assert len(res) == 2
    assert res[1] == [b"cc", b"dd"]
    with pytest.raises(NonAddressableStreamError, match="destination 0"):
        res[0]
    # plain iteration (the single-host idiom) fails LOUDLY on the first
    # remote row instead of consuming a partial matrix
    with pytest.raises(NonAddressableStreamError):
        list(res)
    # the explicit multi-host idiom yields (dst, row) pairs
    assert list(res.items()) == [(1, [b"cc", b"dd"])]


def test_exchange_bytes_single_host_stays_plain(devices):
    """All destinations addressable → the plain nested-list contract is
    unchanged (no wrapper)."""
    from sparkrdma_tpu.parallel.mesh import make_mesh

    ex = TileExchange(make_mesh(4), tile_bytes=1 << 10)
    streams = [
        [bytes([s * 4 + d]) * (16 * (s + d + 1)) for d in range(4)]
        for s in range(4)
    ]
    out = ex.exchange_bytes(streams)
    assert isinstance(out, list)
    assert all(out[d][s] == streams[s][d] for s in range(4) for d in range(4))


def test_plan_tile_quantized_to_pow2_ladder():
    """Sub-tile exchanges quantize the tile to a power-of-two ladder of
    TILE_ALIGN units so the compiled collective shape repeats across
    varying stream sizes (a 20-40s recompile per novel shape on chip)."""
    from sparkrdma_tpu.parallel.exchange import TILE_ALIGN, ExchangePlan

    def plan_for(max_len, conf_tile=4 << 20):
        lengths = np.zeros((4, 4), np.int64)
        lengths[0, 1] = max_len
        return ExchangePlan(lengths, conf_tile)

    seen = {plan_for(n).tile_bytes for n in range(1, 100_000, 777)}
    # ~100k/128 distinct exact tiles collapse onto the pow2 ladder
    assert len(seen) <= 11, seen
    for t in seen:
        assert t % TILE_ALIGN == 0
        u = t // TILE_ALIGN
        assert u & (u - 1) == 0, f"tile {t} not a pow2 of units"
    # at/above the configured tile the shape is pinned to it
    assert plan_for(4 << 20).tile_bytes == 4 << 20
    assert plan_for(64 << 20).tile_bytes == 4 << 20
    assert plan_for((4 << 20) + 1).rounds == 2
    # rounds still cover the payload on the ladder
    p = plan_for(100_001)
    assert p.rounds * p.tile_bytes >= 100_001
