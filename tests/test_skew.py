"""Skew-adaptive partitioning (sparkrdma_tpu/skew/): hot-partition
classification, frame-boundary sub-block planning, the extended-table
marker encoding, and the reader's interleaved fetch + re-sequenced merge
— from pure-function units up through split-vs-unsplit bit-exact e2e
shuffles on every transport engine, with mid-fetch sub-block failure and
delta-sync republish of split entries."""

import time
from collections import defaultdict
from contextlib import contextmanager

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import FetchFailedError
from sparkrdma_tpu.skew import (
    SPLIT_MKEY,
    HeavyHitterSketch,
    PartitionSketch,
    collapse_sub_locations,
    get_skew,
    is_split_marker,
    plan_commit_splits,
    split_targets,
    sub_spans,
)
from sparkrdma_tpu.skew.splitter import make_marker
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.utils.columns import ColumnBatch
from sparkrdma_tpu.utils.ledger import NOOP_TICKET
from sparkrdma_tpu.utils.serde import PickleSerializer
from sparkrdma_tpu.utils.types import BlockLocation

BASE_PORT = 33500


@pytest.fixture(autouse=True)
def registry_on():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    skew = get_skew()
    prev_skew = skew.enabled
    skew.reset()
    yield GLOBAL_REGISTRY
    GLOBAL_REGISTRY.enabled = prev
    skew.enabled = prev_skew
    skew.reset()


# ---------------------------------------------------------------------------
# classification + span planning units
# ---------------------------------------------------------------------------

def test_split_targets_absolute_and_relative():
    # absolute: >= threshold; relative: >= factor * median(nonzero)
    sizes = [100, 0, 5000, 100, 120]
    assert split_targets(sizes, 5000, 0.0, 16) == [2]
    # median of nonzero [100, 100, 120, 5000] (lower middle) = 100;
    # factor 4 → cutoff 400 catches the 5000 even with a huge threshold
    assert split_targets(sizes, 1 << 30, 4.0, 16) == [2]
    # factor <= 0 disables relative detection
    assert split_targets(sizes, 1 << 30, 0.0, 16) == []
    # degenerate knobs never classify
    assert split_targets(sizes, 0, 4.0, 16) == []
    assert split_targets(sizes, 5000, 4.0, 1) == []
    assert split_targets([], 100, 4.0, 16) == []


def test_sub_spans_packing_and_caps():
    frames = [(0, 10), (10, 20), (20, 30), (30, 40)]  # four 10B frames
    # target 20 → pairs
    assert sub_spans(frames, 20, 16) == [(0, 20), (20, 20)]
    # an oversized frame keeps a span of its own (frames indivisible)
    assert sub_spans([(0, 50), (50, 60)], 20, 16) == [(0, 50), (50, 10)]
    # max_subs cap: the last span absorbs the remainder
    assert sub_spans(frames, 10, 3) == [(0, 10), (10, 10), (20, 20)]
    # single frame / everything fits one target → no split
    assert sub_spans([(0, 40)], 10, 16) is None
    assert sub_spans(frames, 100, 16) is None
    assert sub_spans(frames, 0, 16) is None


def test_plan_commit_splits_pickle_frames():
    ser = PickleSerializer(batch_size=100)
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.skewEnabled": "true",
        "spark.shuffle.tpu.skewSplitThreshold": "4k",
    })
    hot = ser.serialize([(i, b"x" * 40) for i in range(500)])  # 5 frames
    cold = ser.serialize([(i, b"x" * 40) for i in range(50)])
    sizes = [len(cold), len(hot), 0]
    plan = plan_commit_splits(ser, {0: cold, 1: hot}, sizes, conf)
    assert list(plan) == [1]
    spans = plan[1]
    assert len(spans) >= 2
    # spans tile the payload contiguously and each is deserializable
    off = 0
    recs = []
    for rel, ln in spans:
        assert rel == off
        recs.extend(ser.deserialize(hot[rel:rel + ln]))
        off += ln
    assert off == len(hot)
    assert recs == list(ser.deserialize(hot))
    # a payload the serializer cannot frame-walk is skipped, not fatal
    plan = plan_commit_splits(ser, {1: b"\xff" * len(hot)}, sizes, conf)
    assert plan == {}


def test_marker_encoding_and_collapse():
    m = make_marker(8, 3)
    assert is_split_marker(m) and m.mkey == SPLIT_MKEY
    assert not m.is_empty  # length carries num_subs >= 2
    assert not is_split_marker(BlockLocation.EMPTY)
    assert not is_split_marker(BlockLocation(0, 10, 1))
    # markers survive the 16B wire entry round-trip (signed mkey)
    rt = BlockLocation.read(memoryview(m.pack()))
    assert rt == m and is_split_marker(rt)
    subs = [BlockLocation(128, 100, 7), BlockLocation(228, 50, 7)]
    assert collapse_sub_locations(subs) == BlockLocation(128, 150, 7)


def test_sketches():
    ps = PartitionSketch(4)
    for pid, n in [(0, 1), (2, 5), (2, 3)]:
        ps.add(pid, n)
    assert ps.records() == [1, 0, 8, 0]
    assert ps.max_records() == 8
    hh = HeavyHitterSketch(capacity=2)
    for ch in "aaaaaabbbc":
        hh.add(ch)
    top = dict(hh.top(2))
    assert max(top, key=top.get) == "a"
    assert hh.top_share() >= 0.5  # MG undercount: 5/10 for 6 true a's


def test_registry_accounting_and_max_fold():
    skew = get_skew()
    s1 = skew.record_commit(7, [10, 900, 0], {1: [(0, 450), (450, 450)]},
                            hot_key_share=0.25)
    assert s1["partitions_split"] == 1 and s1["sub_blocks"] == 2
    assert s1["split_bytes"] == 900 and s1["max_partition_bytes"] == 900
    assert s1["max_hot_key_share_pct"] == 25.0
    skew.record_commit(7, [700, 20, 0], None, hot_key_share=0.1)
    acc = skew.shuffle_stats(7)
    # sums for counts, maxima for max_ keys
    assert acc["partitions_split"] == 1
    assert acc["partitions_nonzero"] == 4
    assert acc["max_partition_bytes"] == 900
    assert acc["max_hot_key_share_pct"] == 25.0
    skew.release_shuffle(7)
    assert skew.shuffle_stats(7) == {}


def test_map_output_ensure_capacity():
    mto = MapTaskOutput(4)
    # a reader snapshot taken BEFORE the grow must not make the grow
    # raise (bytearray resize with a live export → BufferError)
    view = memoryview(mto._buf)
    mto.ensure_capacity(7)
    assert mto.num_partitions == 7
    assert len(view) == 4 * 16  # old snapshot intact
    mto.ensure_capacity(5)  # shrink is a no-op
    assert mto.num_partitions == 7
    for p in range(7):
        mto.put(p, BlockLocation(p * 100, 10, 1))
    assert mto.fill_future.done()
    assert mto.get_location(6) == BlockLocation(600, 10, 1)


# ---------------------------------------------------------------------------
# e2e: split vs unsplit bit-exactness, every engine
# ---------------------------------------------------------------------------

NUM_PARTS = 8
HOT_PID = HashPartitioner(NUM_PARTS).partition("hot-0")


def _hot_key_pool(m, n=40):
    """``n`` distinct sortable keys for map ``m`` that ALL hash into
    HOT_PID — many keys per hot partition keeps the reduce-side k-way
    merge honest, and the per-map namespace keeps cross-map outputs
    byte-comparable (see _hot_records)."""
    part = HashPartitioner(NUM_PARTS)
    out, i = [], 0
    while len(out) < n:
        k = f"hot-m{m}-{i:04d}"
        if part.partition(k) == HOT_PID:
            out.append(k)
        i += 1
    return out


HOT_KEYS = {m: _hot_key_pool(m) for m in range(4)}


def _conf(driver_port, skew_on, extra=None):
    d = {
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "10s",
        "spark.shuffle.tpu.connectTimeout": "5s",
        "spark.shuffle.tpu.skewEnabled": skew_on,
        # far below the hot partition, above the uniform ones
        "spark.shuffle.tpu.skewSplitThreshold": "16k",
        "spark.shuffle.tpu.metrics": True,
    }
    if extra:
        d.update(extra)
    return TpuShuffleConf(d)


@contextmanager
def _cluster(netkind, driver_port, skew_on, extra=None):
    extra = dict(extra or {})
    if netkind == "tcp-threaded":
        extra["spark.shuffle.tpu.transportAsyncDispatcher"] = "off"
    if netkind == "loopback":
        shared = LoopbackNetwork()

        def mknet():
            return shared
    else:
        def mknet():
            return TcpNetwork()
    driver = TpuShuffleManager(
        _conf(driver_port, skew_on, extra), is_driver=True,
        network=mknet(), port=driver_port, stage_to_device=False,
    )
    executors = [
        TpuShuffleManager(
            _conf(driver_port, skew_on, extra), is_driver=False,
            network=mknet(), port=driver_port + 10 + i * 10,
            executor_id=str(i), stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    try:
        yield driver, executors
    finally:
        for m in executors + [driver]:
            m.stop()


def _hot_records(m, n_hot=9000, n_cold=300):
    """One map task's records: ~30x skew into HOT_PID across 40
    distinct sortable keys, plus a uniform tail.  >1 pickle batch per
    hot bucket so the commit has frames to cut at, and each key repeats
    across batches so equal keys SPAN sub-block boundaries — the case
    the reader's sub sequencing must keep stable.  Keys are unique per
    MAP (``-m`` suffix): cross-map equal-key order is fetch-arrival-
    dependent in the pre-PR path already (the existing e2e suites
    compare per-key multisets for that reason), so byte-comparing
    whole outputs is only sound without cross-map key collisions."""
    pool = HOT_KEYS[m]
    recs = [
        (pool[j % len(pool)], bytes([m, j % 251]) * 30)
        for j in range(n_hot)
    ]
    recs += [
        (f"k{j % 61}-m{m}", bytes([m, j % 251]) * 30)
        for j in range(n_cold)
    ]
    return recs


def _run_shuffle(driver, executors, shuffle_id, key_ordering=True):
    """Write 4 skewed map tasks across both executors, read every
    partition from both sides; returns (per-reduce ordered outputs,
    commit-time skew stats)."""
    num_maps = 4
    handle = driver.register_shuffle(
        shuffle_id, num_maps, HashPartitioner(NUM_PARTS),
        key_ordering=key_ordering,
    )
    maps_by_host = defaultdict(list)
    for m in range(num_maps):
        ex = executors[m % 2]
        w = ex.get_writer(handle, m)
        w.write(_hot_records(m))
        w.stop(True)
        maps_by_host[ex.local_smid].append(m)
    stats = get_skew().shuffle_stats(shuffle_id)
    out = []
    for i, ex in enumerate(executors):
        reader = ex.get_reader(
            handle, i * 4, i * 4 + 4, dict(maps_by_host)
        )
        out.append(list(reader.read()))
    return out, stats


@pytest.mark.parametrize("netkind,port_off", [
    ("loopback", 0),
    ("tcp-async", 40),
    ("tcp-threaded", 80),
])
@pytest.mark.parametrize("decode_threads", [0, 4])
def test_split_vs_unsplit_bit_exact(netkind, port_off, decode_threads):
    """The PR's core invariant: skewEnabled=on produces BYTE-identical
    reduce output to =off — same records, same order (key_ordering
    makes the order fully determined) — while actually splitting and
    re-sequencing sub-blocks, on every engine, serial and pipelined
    decode."""
    port = BASE_PORT + port_off + (0 if decode_threads else 160)
    extra = {"spark.shuffle.tpu.decodeThreads": decode_threads}
    with _cluster(netkind, port, False, extra) as (driver, executors):
        golden, stats_off = _run_shuffle(driver, executors, 11)
    assert stats_off.get("partitions_split", 0) == 0
    get_skew().reset()
    fanin0 = GLOBAL_REGISTRY.histogram("skew_merge_fanin").count
    with _cluster(netkind, port + 400, True, extra) as (driver, executors):
        got, stats_on = _run_shuffle(driver, executors, 11)
    assert stats_on["partitions_split"] >= 4  # hot pid split on all maps
    assert stats_on["sub_blocks"] >= 2 * stats_on["partitions_split"]
    assert got == golden  # bit-exact: same records, same order
    # at least one reader actually merged a split partition's sub-runs
    assert GLOBAL_REGISTRY.histogram("skew_merge_fanin").count > fanin0


def test_columnar_split_bit_exact_loopback():
    """The columnar zero-copy commit (_commit_direct) splits at its
    per-(batch, partition) frame boundaries and stays bit-exact."""
    extra = {"spark.shuffle.tpu.serializer": "columnar"}
    port = BASE_PORT + 320

    def run(skew_on, port):
        with _cluster("loopback", port, skew_on, extra) as (drv, exs):
            handle = drv.register_shuffle(
                5, 2, HashPartitioner(NUM_PARTS), key_ordering=True,
            )
            maps_by_host = defaultdict(list)
            rng = np.random.default_rng(3)
            for m in range(2):
                ex = exs[m % 2]
                w = ex.get_writer(handle, m)
                for _ in range(6):  # several batches → several frames
                    keys = np.where(
                        rng.random(4000) < 0.9,
                        np.int64(HOT_PID),
                        rng.integers(0, 1000, 4000),
                    )
                    w.write_columns(ColumnBatch(
                        keys,
                        rng.integers(0, 1 << 40, 4000).astype(np.int64),
                    ))
                w.stop(True)
                maps_by_host[ex.local_smid].append(m)
            stats = get_skew().shuffle_stats(5)
            out = []
            for i, ex in enumerate(exs):
                r = ex.get_reader(
                    handle, i * 4, i * 4 + 4, dict(maps_by_host)
                )
                out.append([(int(k), int(v)) for k, v in r.read()])
            return out, stats

    golden, _ = run(False, port)
    get_skew().reset()
    got, stats = run(True, port + 40)
    assert stats["partitions_split"] >= 1
    assert got == golden


def test_uniform_workload_is_identity_noop():
    """skewEnabled=on with uniform partition sizes: nothing classifies,
    no markers are emitted, output matches =off exactly."""
    def run(skew_on, port):
        with _cluster("loopback", port, skew_on) as (drv, exs):
            handle = drv.register_shuffle(
                9, 2, HashPartitioner(NUM_PARTS), key_ordering=True,
            )
            maps_by_host = defaultdict(list)
            for m in range(2):
                ex = exs[m % 2]
                w = ex.get_writer(handle, m)
                w.write([
                    (f"k{j % 200}", bytes([m, j % 251]) * 20)
                    for j in range(2000)
                ])
                w.stop(True)
                maps_by_host[ex.local_smid].append(m)
            stats = get_skew().shuffle_stats(9)
            out = []
            for i, ex in enumerate(exs):
                r = ex.get_reader(
                    handle, i * 4, i * 4 + 4, dict(maps_by_host)
                )
                out.append(list(r.read()))
            return out, stats

    golden, _ = run(False, BASE_PORT + 480)
    get_skew().reset()
    got, stats = run(True, BASE_PORT + 520)
    assert stats.get("partitions_split", 0) == 0
    assert got == golden
    # balance telemetry still recorded (satellite: skew view while off)
    assert stats.get("partitions_nonzero", 0) > 0


def test_subblock_fetch_failure_fails_stage_and_releases_reorder():
    """Mid-fetch failure of a group carrying a sub-block: the reader
    surfaces FetchFailedError (stage retry) instead of hanging on the
    never-arriving sub-run, and cleanup releases any parked reorder
    tickets."""
    port = BASE_PORT + 560
    with _cluster("tcp-async", port, True) as (driver, executors):
        handle = driver.register_shuffle(
            13, 2, HashPartitioner(NUM_PARTS), key_ordering=True,
        )
        maps_by_host = defaultdict(list)
        for m in range(2):
            ex = executors[m % 2]
            w = ex.get_writer(handle, m)
            w.write(_hot_records(m))
            w.stop(True)
            maps_by_host[ex.local_smid].append(m)
        assert get_skew().shuffle_stats(13)["partitions_split"] >= 2
        reader = executors[0].get_reader(
            handle, 0, NUM_PARTS, dict(maps_by_host)
        )
        orig_issue = reader._issue
        state = {"tripped": False}

        def failing_issue(fetch):
            if fetch.tags is not None and not state["tripped"]:
                state["tripped"] = True
                with reader._pending_lock:
                    reader._bytes_in_flight -= fetch.total_bytes
                fetch.win_tkt.release()
                if fetch.qos_tkt is not NOOP_TICKET:
                    fetch.qos_tkt.release()
                reader._fail(FetchFailedError(
                    fetch.host.host, 13, "injected sub-block loss"
                ))
                return
            orig_issue(fetch)

        reader._issue = failing_issue
        with pytest.raises(FetchFailedError):
            list(reader.read())
        assert state["tripped"]
        assert not reader._sub_buf


def test_delta_republish_of_split_entries():
    """Delta-sync republish (epoch-tagged dirty runs) of a table
    holding markers + aux rows: the driver re-applies the extended
    table and reads stay bit-exact — the wire plane never learned
    about splitting."""
    port = BASE_PORT + 600
    with _cluster("tcp-async", port, True) as (driver, executors):
        handle = driver.register_shuffle(
            17, 2, HashPartitioner(NUM_PARTS), key_ordering=True,
        )
        maps_by_host = defaultdict(list)
        mtos = []
        for m in range(2):
            ex = executors[m % 2]
            w = ex.get_writer(handle, m)
            w.write(_hot_records(m))
            mtos.append((ex, m, w.stop(True)))
            maps_by_host[ex.local_smid].append(m)

        def read_all():
            out = []
            for i, ex in enumerate(executors):
                r = ex.get_reader(
                    handle, i * 4, i * 4 + 4, dict(maps_by_host)
                )
                out.append(list(r.read()))
            return out

        first = read_all()
        # dirty EVERY entry (markers and aux rows included) and
        # republish: ships as a fresh full-table delta at epoch+1
        for ex, m, mto in mtos:
            assert mto.num_partitions > NUM_PARTS  # table extended
            mto.mark_dirty(0, mto.num_partitions - 1)
            ex.publish_map_output(17, m, mto)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            tbl = driver._get_or_create_mto(
                17, mtos[0][0].local_smid, mtos[0][1]
            )
            if tbl.fill_future.done():
                break
            time.sleep(0.01)
        assert read_all() == first


def test_local_reads_collapse_markers():
    """A driver-local (single-manager) shuffle with splits: local reads
    resolve markers via collapse (one whole-span read), never fetch
    sub-blocks, and stay bit-exact."""
    port = BASE_PORT + 640
    mgr = TpuShuffleManager(
        _conf(port, True), is_driver=True,
        network=LoopbackNetwork(), port=port, stage_to_device=False,
    )
    try:
        handle = mgr.register_shuffle(
            19, 1, HashPartitioner(NUM_PARTS), key_ordering=True,
        )
        recs = _hot_records(0)
        w = mgr.get_writer(handle, 0)
        w.write(recs)
        w.stop(True)
        assert get_skew().shuffle_stats(19)["partitions_split"] >= 1
        reader = mgr.get_reader(
            handle, 0, NUM_PARTS, {mgr.local_smid: [0]}
        )
        got = list(reader.read())
        assert got == sorted(recs, key=lambda kv: kv[0])
        assert reader.metrics.remote_blocks == 0
    finally:
        mgr.stop()


def test_sequence_sub_block_reorders_and_accounts():
    """Unit drive of the reorder buffer: every sub-block parks
    (ledger-tracked) until the full sibling set lands, then the whole
    partition emits contiguously in sub order and all per-partition
    state clears."""
    port = BASE_PORT + 680
    mgr = TpuShuffleManager(
        _conf(port, True), is_driver=True,
        network=LoopbackNetwork(), port=port, stage_to_device=False,
    )
    try:
        handle = mgr.register_shuffle(23, 1, HashPartitioner(2))
        r = mgr.get_reader(handle, 0, 1, {})
        assert list(r._sequence_sub_block((5, 0, 1, 3), b"B")) == []
        assert list(r._sequence_sub_block((5, 0, 0, 3), b"A")) == []
        assert r._sub_buf and r.metrics.remote_blocks == 0
        assert list(r._sequence_sub_block((5, 0, 2, 3), b"C")) == [
            b"A", b"B", b"C",
        ]
        assert not r._sub_buf
        assert r.metrics.remote_blocks == 3
        # independent partitions sequence independently
        assert list(r._sequence_sub_block((5, 1, 0, 2), b"x")) == []
        assert list(r._sequence_sub_block((6, 0, 1, 2), b"y")) == []
        assert set(r._sub_buf) == {(5, 1), (6, 0)}
        assert list(r._sequence_sub_block((6, 0, 0, 2), b"z")) == [
            b"z", b"y",
        ]
        # _cleanup releases tickets parked by the abandoned (5, 1) set
        r._cleanup()
        assert not r._sub_buf
    finally:
        mgr.stop()
