"""Tiered block store (memory/tier.py): out-of-core residency for
file-backed map outputs — hot pooled rows over cold mapped files, LRU +
pinned eviction, and hint/readahead prefetch — exercised from the unit
level (blocks, pins, budget) up through bit-exact e2e shuffles under
forced demotion/promotion churn on every transport engine."""

import gc
import threading
import time
from collections import defaultdict
from contextlib import contextmanager

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.arena import ArenaManager
from sparkrdma_tpu.memory.mapped_file import MappedFile
from sparkrdma_tpu.memory.tier import TieredBlockStore
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.resolver import ShuffleBlockResolver
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork

BASE_PORT = 29500


@pytest.fixture(autouse=True)
def registry_on():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    yield GLOBAL_REGISTRY
    GLOBAL_REGISTRY.enabled = prev


def _counter(name):
    return GLOBAL_REGISTRY.counter(name).value


# ---------------------------------------------------------------------------
# store-level units
# ---------------------------------------------------------------------------

def _make_entry(store, arena, n_blocks=8, block=8192, seed=7):
    """One adopted output of ``n_blocks`` equal blocks with a
    deterministic pattern; returns (segment, pattern)."""
    rng = np.random.default_rng(seed)
    pattern = rng.integers(0, 256, n_blocks * block, dtype=np.uint8)
    mf = MappedFile(pattern.tobytes(), direct_write=False, defer_map=True)
    spans = [(i * block, block) for i in range(n_blocks)]
    seg = store.adopt(mf, spans, n_blocks * block, 0, arena)
    return seg, pattern


def _expect(seg, pattern, off, ln):
    got = seg.read(off, ln)
    arr = got if isinstance(got, np.ndarray) else np.frombuffer(
        memoryview(got), np.uint8)
    assert np.array_equal(arr, pattern[off : off + ln]), (off, ln)


def test_lazy_mapping_and_basic_tiers():
    """A fresh adoption maps nothing; reads serve bit-exact from cold;
    a warmed block serves hot (hit counter) as a zero-copy view."""
    store = TieredBlockStore(hot_bytes=64 << 10)
    arena = ArenaManager()
    seg, pattern = _make_entry(store, arena)
    assert seg.entry.mf.array is None  # deferred: nothing mapped yet
    h0, m0 = _counter("tier_hits_total"), _counter("tier_misses_total")
    _expect(seg, pattern, 0, 8192)          # whole-block cold read
    assert _counter("tier_misses_total") == m0 + 1
    assert store.warm(seg.mkey, 8192, 8192) == 1
    _expect(seg, pattern, 8192, 8192)       # now a hot hit
    assert _counter("tier_hits_total") == h0 + 1
    assert store.stats()["hot_blocks"] == 1
    arena.release(seg.mkey)
    assert store.stats() == {
        "entries": 0, "hot_blocks": 0, "hot_bytes": 0,
        "hot_budget": 64 << 10,
    }


def test_subrange_read_promotes_whole_block():
    """The striped serve shape: a sub-range read promotes its WHOLE
    block (one disk read serves every stripe), sibling sub-ranges hit
    hot, and concurrent sub-ranges of one cold block share a single
    promotion via the loading event."""
    store = TieredBlockStore(hot_bytes=64 << 10)
    arena = ArenaManager()
    seg, pattern = _make_entry(store, arena, n_blocks=2, block=32768)
    p0 = _counter("tier_promotes_total")
    _expect(seg, pattern, 100, 1000)
    assert _counter("tier_promotes_total") == p0 + 1
    assert store.stats()["hot_blocks"] == 1
    h0 = _counter("tier_hits_total")
    _expect(seg, pattern, 8000, 9000)       # sibling stripe: hot
    _expect(seg, pattern, 0, 32768)         # whole block: hot
    assert _counter("tier_hits_total") == h0 + 2
    # concurrent cold sub-ranges: exactly one more promotion
    p1 = _counter("tier_promotes_total")
    errs = []

    def rd(off, ln):
        try:
            _expect(seg, pattern, off, ln)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=rd, args=(32768 + i * 4096, 4096))
          for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
        assert not t.is_alive()
    assert not errs, errs
    assert _counter("tier_promotes_total") == p1 + 1


def test_eviction_never_tears_inflight_serve():
    """The PR 7 wedged-serve shape: a serve-pool worker holds a hot
    view mid-serve while promotion pressure wants its block's budget —
    the pinned block is REFUSED eviction (counted), the view stays
    bit-exact, and once the serve completes (view collected) the block
    demotes normally."""
    from sparkrdma_tpu.transport.node import Node

    block = 8192
    store = TieredBlockStore(hot_bytes=2 * block)
    arena = ArenaManager()
    seg, pattern = _make_entry(store, arena, n_blocks=8, block=block)
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportServeThreads": 1,
    })
    node = Node(("127.0.0.1", BASE_PORT + 90), conf)
    gate = threading.Event()
    served = threading.Event()
    res = {}

    def wedged_serve():
        # sub-range read: promotes block 0 and pins the hot view
        res["view"] = seg.read(0, block - 512)
        served.set()
        gate.wait(30)  # serve stays in flight, view live

    try:
        node.submit_serve(wedged_serve, (), cost=block)
        assert served.wait(10)
        r0 = _counter("tier_evict_refusals_total")
        # budget holds 2 blocks; promoting 4 more must evict — but
        # never the pinned block 0
        for i in range(1, 5):
            store.warm(seg.mkey, i * block, block)
        assert _counter("tier_evict_refusals_total") > r0
        assert store.stats()["hot_bytes"] <= 2 * block
        assert np.array_equal(res["view"], pattern[: block - 512])
        gate.set()
        del res["view"]
        gc.collect()
        # unpinned now: the next promotion may take block 0's budget
        d0 = _counter("tier_demotes_total")
        store.warm(seg.mkey, 5 * block, block)
        store.warm(seg.mkey, 6 * block, block)
        assert _counter("tier_demotes_total") > d0
        assert store.stats()["hot_bytes"] <= 2 * block
    finally:
        gate.set()
        node.stop()


def test_prefetch_hints_vs_out_of_order_reads():
    """Hint-driven warming in fetch-plan order must stay bit-exact
    when the actual reads arrive in a DIFFERENT order (stripe
    completions reorder freely), and prefetched blocks consumed by
    reads count as useful."""
    block = 4096
    store = TieredBlockStore(hot_bytes=6 * block)
    arena = ArenaManager()
    seg, pattern = _make_entry(store, arena, n_blocks=16, block=block)
    u0 = _counter("tier_prefetch_useful_total")
    for i in range(16):  # the reader's plan order
        store.warm(seg.mkey, i * block, block)
    assert store.stats()["hot_bytes"] <= 6 * block
    order = list(range(16))
    np.random.default_rng(3).shuffle(order)
    for i in order:  # out-of-order arrival
        _expect(seg, pattern, i * block, block)
    assert _counter("tier_prefetch_useful_total") > u0
    assert store.stats()["hot_bytes"] <= 6 * block


def test_budget_bounding_without_deadlock():
    """A hot budget smaller than one block never deadlocks or fails:
    oversized blocks serve cold (clamped out of promotion), concurrent
    readers all complete, and hot bytes never exceed the budget."""
    store = TieredBlockStore(hot_bytes=4096)
    arena = ArenaManager()
    seg, pattern = _make_entry(store, arena, n_blocks=4, block=16384)
    peak = [0]
    stop = threading.Event()

    def sample():
        while not stop.is_set():
            peak[0] = max(peak[0], store.stats()["hot_bytes"])
            time.sleep(0.001)

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    errs = []

    def rd(i):
        try:
            for _ in range(4):
                _expect(seg, pattern, i * 16384, 16384)
                _expect(seg, pattern, i * 16384 + 100, 2000)  # sub-range
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=rd, args=(i % 4,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
        assert not t.is_alive(), "tier read deadlocked under tiny budget"
    stop.set()
    sampler.join(timeout=5)
    assert not errs, errs
    assert peak[0] <= 4096
    assert store.stats()["hot_blocks"] == 0  # nothing ever fit


def test_lazy_registration_and_never_read_counter(tmp_path):
    """The eager-registration fix: a file-backed commit maps nothing
    up front, and releasing a shuffle counts the committed bytes its
    readers never touched (what the old whole-output registration paid
    for every time)."""
    arena = ArenaManager()
    store = TieredBlockStore(hot_bytes=1 << 20)
    resolver = ShuffleBlockResolver(
        arena, node=None, stage_to_device=False,
        spill_dir=str(tmp_path), tier_store=store,
    )
    parts = [bytes([i]) * 1000 for i in range(10)]
    resolver.commit_map_output(5, 0, parts, prefer_file_backed=True)
    entry = next(iter(store._by_mkey.values()))
    assert entry.mf.array is None  # nothing mapped at commit
    assert bytes(memoryview(resolver.get_local_block(5, 0, 3))) == parts[3]
    assert bytes(memoryview(resolver.get_local_block(5, 0, 7))) == parts[7]
    n0 = _counter("tier_bytes_never_read_total")
    resolver.remove_shuffle(5)
    assert _counter("tier_bytes_never_read_total") == n0 + 8 * 1000


# ---------------------------------------------------------------------------
# e2e: bit-exact shuffles under forced churn, every engine
# ---------------------------------------------------------------------------

def _conf(driver_port, prefetch, extra=None):
    d = {
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "10s",
        "spark.shuffle.tpu.connectTimeout": "5s",
        # every commit file-backed through the tier, tiny hot budget
        "spark.shuffle.tpu.fileBackedCommitBytes": 1,
        "spark.shuffle.tpu.tierHotBytes": "24k",
        "spark.shuffle.tpu.tierPrefetch": prefetch,
    }
    if extra:
        d.update(extra)
    return TpuShuffleConf(d)


@contextmanager
def _cluster(netkind, driver_port, prefetch):
    extra = {}
    if netkind == "tcp-threaded":
        extra["spark.shuffle.tpu.transportAsyncDispatcher"] = "off"
    if netkind == "loopback":
        shared = LoopbackNetwork()

        def mknet():
            return shared
    else:
        def mknet():
            return TcpNetwork()
    driver = TpuShuffleManager(
        _conf(driver_port, prefetch, extra), is_driver=True,
        network=mknet(), port=driver_port, stage_to_device=False,
    )
    executors = [
        TpuShuffleManager(
            _conf(driver_port, prefetch, extra), is_driver=False,
            network=mknet(), port=driver_port + 10 + i * 10,
            executor_id=str(i), stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    try:
        yield driver, executors
    finally:
        for m in executors + [driver]:
            m.stop()


@pytest.mark.parametrize("netkind,port_off", [
    ("loopback", 0),
    ("tcp-async", 40),
    ("tcp-threaded", 80),
])
@pytest.mark.parametrize("prefetch", [True, False])
def test_e2e_bit_exact_under_churn(netkind, port_off, prefetch):
    """Full shuffle over tiered (file-backed) outputs with the hot
    budget far below the dataset, plus an explicit whole-store warm
    sweep between write and read to force demotion/promotion churn:
    results stay bit-exact on every engine, prefetch on or off."""
    # distinct port block per parametrization: a TCP listener from the
    # previous case may still be draining on a shared port
    port = BASE_PORT + 100 + port_off + (0 if prefetch else 200)
    with _cluster(netkind, port, prefetch) as (driver, executors):
        num_maps, num_parts = 4, 8
        handle = driver.register_shuffle(
            3, num_maps, HashPartitioner(num_parts)
        )
        maps_by_host = defaultdict(list)
        expected = defaultdict(list)
        for m in range(num_maps):
            ex = executors[m % 2]
            recs = [
                (f"k{j % 17}", bytes([m, j % 251]) * 60)
                for j in range(250)
            ]
            for k, v in recs:
                expected[k].append(v)
            w = ex.get_writer(handle, m)
            w.write(recs)
            w.stop(True)
            maps_by_host[ex.local_smid].append(m)
        d0 = _counter("tier_demotes_total")
        for ex in executors:
            # churn: demand-promote EVERY committed block (sub-range
            # reads take the promoting path) through the tiny budget —
            # demotions cascade as later blocks displace earlier ones
            with ex.tier_store._lock:
                entries = list(ex.tier_store._by_mkey.values())
            for e in entries:
                seg = ex.arena.get(e.mkey)
                for blk in e.blocks:
                    if blk.length > 1:
                        seg.read(blk.offset, blk.length - 1)
            assert ex.tier_store.stats()["hot_bytes"] <= 24 << 10
        assert _counter("tier_demotes_total") > d0  # churn really ran
        got = defaultdict(list)
        for i, ex in enumerate(executors):
            reader = ex.get_reader(
                handle, i * 4, i * 4 + 4, dict(maps_by_host)
            )
            for k, v in reader.read():
                got[k].append(v)
            assert reader.metrics.remote_blocks > 0
        assert set(got) == set(expected)
        for k in expected:
            assert sorted(got[k]) == sorted(expected[k]), k
        if prefetch:
            # the reader announced its plan and the responder warmed it
            assert _counter("tier_hint_msgs_total") > 0
            assert _counter("tier_hint_blocks_total") > 0
        for ex in executors:
            assert ex.tier_store.stats()["hot_bytes"] <= 24 << 10


def test_pin_finalizer_lifecycle_live_and_after_ledger_stop():
    """Regression for the GC-tied pin lifecycle: a live consumer view
    settles its ``tier.pins`` ticket when collected, and a finalizer
    firing AFTER the ledger stopped (interpreter-shutdown ordering:
    the manager stops the ledger, then cyclic GC drops the last view)
    is a silent no-op — never a DoubleReleaseError out of the GC."""
    from sparkrdma_tpu.utils.ledger import get_resource_ledger

    led = get_resource_ledger()
    led.reset()
    led.enabled = True
    try:
        store = TieredBlockStore(hot_bytes=1 << 20)
        arena = ArenaManager()
        seg, pattern = _make_entry(store, arena)
        store.warm(seg.mkey, 0, 8192)
        view = seg.read(0, 8192)  # hot: a pinned zero-copy view
        assert isinstance(view, np.ndarray)
        assert led.outstanding().get("tier.pins") == 1
        del view
        gc.collect()  # live finalizer: the pin settles
        assert led.outstanding().get("tier.pins") is None
        assert led.double_releases() == 0

        late = seg.read(8192, 8192) if store.warm(
            seg.mkey, 8192, 8192
        ) else seg.read(0, 8192)
        assert led.outstanding().get("tier.pins") == 1
        led.stop(raise_on_leak=False)  # the manager stopped first
        del late
        gc.collect()  # late finalizer: stale epoch, silent no-op
        assert led.double_releases() == 0
        arena.release(seg.mkey)
    finally:
        led.enabled = False
        led.reset()
