"""Pallas one-pass scan kernels vs the jnp log-step references.

Runs in interpret mode on the CPU harness; semantics must match the
exact implementations they replace on TPU (ops/segment.py fills/scans,
models/join.py probe fill) including the unspecified-before-first-flag
contract (compared only under the returned flag mask).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.ops.scan_kernels import (
    _BLOCK,
    scan_flagged,
)
from sparkrdma_tpu.ops.segment import _ff_run_carry, segmented_scan


def _sizes():
    # within one block, exact block, crossing blocks, many blocks
    return [1, 127, 128, 1000, _BLOCK, _BLOCK + 1, 3 * _BLOCK + 4097]


@pytest.mark.parametrize("n", _sizes())
def test_fill_matches_run_carry(n):
    rng = np.random.default_rng(n)
    flag = rng.random(n) < 0.01
    a = rng.integers(0, 1 << 30, n, dtype=np.int32)
    b = rng.integers(0, 1 << 30, n, dtype=np.int32)
    want_f, (wa, wb) = _ff_run_carry(
        jnp.asarray(flag), (jnp.asarray(a), jnp.asarray(b))
    )
    got_f, (ga, gb) = scan_flagged(
        "fill", jnp.asarray(flag), (jnp.asarray(a), jnp.asarray(b)),
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_f), np.asarray(want_f))
    m = np.asarray(want_f)
    np.testing.assert_array_equal(np.asarray(ga)[m], np.asarray(wa)[m])
    np.testing.assert_array_equal(np.asarray(gb)[m], np.asarray(wb)[m])


@pytest.mark.parametrize("kind", ["add", "min", "max"])
@pytest.mark.parametrize("n", [1, 1000, _BLOCK + 1])
def test_segmented_ops_match(kind, n):
    rng = np.random.default_rng(hash((kind, n)) % (1 << 31))
    heads = rng.random(n) < 0.05
    vals = rng.integers(-1000, 1000, n, dtype=np.int32)
    op = {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum}[kind]
    ident = {
        "add": np.int32(0),
        "min": np.iinfo(np.int32).max,
        "max": np.iinfo(np.int32).min,
    }[kind]
    want = segmented_scan(jnp.asarray(vals), jnp.asarray(heads), op, ident)
    _f, (got,) = scan_flagged(
        "add" if kind == "add" else kind,
        jnp.asarray(heads), (jnp.asarray(vals),), interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fill_edge_flags():
    # all-false flags: output flag all false; all-true: identity fill
    n = 300
    a = np.arange(n, dtype=np.int32)
    f0, (x0,) = scan_flagged(
        "fill", jnp.zeros(n, bool), (jnp.asarray(a),), interpret=True
    )
    assert not np.asarray(f0).any()
    f1, (x1,) = scan_flagged(
        "fill", jnp.ones(n, bool), (jnp.asarray(a),), interpret=True
    )
    assert np.asarray(f1).all()
    np.testing.assert_array_equal(np.asarray(x1), a)


def test_plain_cumsum_via_add_scan():
    n = 2 * _BLOCK + 999
    rng = np.random.default_rng(3)
    vals = rng.integers(-50, 50, n, dtype=np.int32)
    _f, (got,) = scan_flagged(
        "add", jnp.zeros(n, bool), (jnp.asarray(vals),), interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.cumsum(vals))


def test_probe_fill_semantics_via_kernel():
    """The join probe's fill = 'fill' over (key, val) with dim flags;
    found mask must match the jnp probe on a sorted packed stream."""
    from sparkrdma_tpu.models.join import (
        _ROLE_DIM,
        _ROLE_FACT,
        _probe_fill,
    )

    rng = np.random.default_rng(17)
    n = 5000
    keys = np.sort(rng.integers(0, 300, n).astype(np.uint32))
    role = np.full(n, _ROLE_FACT, np.uint32)
    # one dim row at each key run head, for ~half the keys
    heads = np.flatnonzero(np.diff(keys, prepend=-1) != 0)
    dim_at = heads[::2]
    role[dim_at] = _ROLE_DIM
    pay = rng.integers(0, 1 << 30, n).astype(np.uint32)
    want_val, want_found = _probe_fill(
        jnp.asarray(keys), jnp.asarray(role), jnp.asarray(pay)
    )
    flag = jnp.asarray(role == _ROLE_DIM)
    got_f, (gk, gv) = scan_flagged(
        "fill", flag, (jnp.asarray(keys), jnp.asarray(pay)),
        interpret=True,
    )
    got_found = (
        jnp.asarray(role == _ROLE_FACT) & got_f
        & (gk == jnp.asarray(keys))
    )
    np.testing.assert_array_equal(
        np.asarray(got_found), np.asarray(want_found)
    )
    m = np.asarray(want_found)
    np.testing.assert_array_equal(
        np.asarray(gv)[m], np.asarray(want_val)[m]
    )


def test_dispatch_wiring_produces_identical_results(monkeypatch):
    """Force the TPU dispatch gates ON (kernel routed through interpret
    mode) and check the join probe and keyed reductions produce exactly
    the jnp-path results — catches arg-order/flag-convention bugs in
    the wiring that the isolated kernel tests cannot."""
    import sparkrdma_tpu.ops.scan_kernels as sk
    from sparkrdma_tpu.models.join import (
        _ROLE_DIM,
        _ROLE_FACT,
        _probe_fill,
    )
    from sparkrdma_tpu.ops.segment import (
        aggregate_by_key_local,
        reduce_by_key_local,
    )

    n = sk.MIN_KERNEL_ELEMS  # large enough to pass the size gate
    rng = np.random.default_rng(123)
    keys = np.sort(rng.integers(0, 500, n).astype(np.uint32))
    role = np.full(n, _ROLE_FACT, np.uint32)
    heads = np.flatnonzero(np.diff(keys, prepend=-1) != 0)
    role[heads[::3]] = _ROLE_DIM
    pay = rng.integers(0, 1 << 30, n).astype(np.uint32)

    rkeys = rng.integers(0, 97, n, dtype=np.int32)
    rvals = rng.integers(-100, 100, n, dtype=np.int32)

    def run_all():
        pf = _probe_fill(
            jnp.asarray(keys), jnp.asarray(role), jnp.asarray(pay)
        )
        red = reduce_by_key_local(
            jnp.asarray(rkeys), jnp.asarray(rvals), None
        )
        agg = aggregate_by_key_local(
            jnp.asarray(rkeys), jnp.asarray(rvals), None
        )
        return pf, red, agg

    # reference: jnp log-step paths (kernels off)
    monkeypatch.setattr(sk, "use_scan_kernels", lambda: False)
    (wv, wf), wred, wagg = run_all()

    # kernel path: gate on, interpret-mode execution
    real = sk.scan_flagged
    monkeypatch.setattr(
        sk, "scan_flagged",
        lambda kind, flag, cols: real(kind, flag, cols, interpret=True),
    )
    monkeypatch.setattr(sk, "use_scan_kernels", lambda: True)
    (gv, gf), gred, gagg = run_all()

    np.testing.assert_array_equal(np.asarray(gf), np.asarray(wf))
    m = np.asarray(wf)
    np.testing.assert_array_equal(np.asarray(gv)[m], np.asarray(wv)[m])
    for w, g in zip(wred, gred):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    for w, g in zip(wagg, gagg):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
