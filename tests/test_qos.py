"""Multi-tenant QoS (sparkrdma_tpu/qos/): weighted credit brokering,
FIFO handoff, priority classes + aging, lane reserve, admission
control, tier share protection, qosEnabled=false identity, and a
lockDebug stress with brokers active."""

import threading
import time
from collections import defaultdict

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.arena import ArenaManager
from sparkrdma_tpu.memory.mapped_file import MappedFile
from sparkrdma_tpu.memory.tier import TieredBlockStore
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.qos import (
    BULK,
    INTERACTIVE,
    ClassedTaskQueue,
    CreditLedger,
    TenantRegistry,
    WeightedCreditBroker,
)
from sparkrdma_tpu.qos.registry import GLOBAL_QOS
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.transport.node import _LanePool

BASE_PORT = 30500


@pytest.fixture(autouse=True)
def registry_on():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    yield GLOBAL_REGISTRY
    GLOBAL_REGISTRY.enabled = prev


@pytest.fixture(autouse=True)
def qos_reset():
    """Isolate the process-global tenant registry per test."""
    prev = GLOBAL_QOS.enabled
    GLOBAL_QOS.reset()
    yield GLOBAL_QOS
    GLOBAL_QOS.enabled = prev
    GLOBAL_QOS.reset()


def _counter(name, **labels):
    return GLOBAL_REGISTRY.counter(name, **labels).value


# ---------------------------------------------------------------------------
# CreditLedger policy units
# ---------------------------------------------------------------------------

def _ledger_with_tenants():
    qos = TenantRegistry(enabled=True)
    a = qos.tenant("A", weight=3)
    b = qos.tenant("B", weight=1)
    return CreditLedger("test", 4000, qos=qos), a, b


def test_work_conservation_single_tenant_gets_everything():
    """An only-active tenant borrows the WHOLE budget — weights cap
    nothing while nobody else wants credits."""
    ledger, a, _b = _ledger_with_tenants()
    taken = 0
    while ledger.can_take(a, 100):
        ledger.take(a, 100)
        taken += 100
    assert taken == 4000
    assert ledger.free == 0


def test_reclaim_on_demand_and_share_convergence():
    """A (w=3) borrowed 100%; once B (w=1) waits, A's further grants
    pause (reclaim) and steady-state churn converges to the weighted
    3000/1000 split of the 4000-byte budget."""
    ledger, a, b = _ledger_with_tenants()
    while ledger.can_take(a, 100):
        ledger.take(a, 100)
    waiting = {"B": b}
    # reclaim-on-demand: the over-share borrower is paused while the
    # deprived tenant waits...
    ledger.put(a, 100)
    assert not ledger.can_take(a, 100, waiting)
    # ...and the deprived tenant takes the freed credits
    assert ledger.can_take(b, 100, waiting)
    ledger.take(b, 100)
    # steady-state churn: both tenants release one chunk per round and
    # greedily re-acquire — usage must converge to the weighted shares
    waiting = {"A": a, "B": b}
    for _round in range(80):
        if ledger.used(a) >= 100:
            ledger.put(a, 100)
        if ledger.used(b) >= 100:
            ledger.put(b, 100)
        for t in (a, b):
            while ledger.can_take(t, 100, waiting):
                ledger.take(t, 100)
    assert ledger.used(a) == 3000
    assert ledger.used(b) == 1000
    assert ledger.free == 0


def test_inflight_quota_caps_one_tenant():
    qos = TenantRegistry(enabled=True)
    t = qos.tenant("q", max_inflight=150)
    ledger = CreditLedger("infl", 1000, qos=qos, quota_inflight=True)
    assert ledger.can_take(t, 100)
    ledger.take(t, 100)
    assert not ledger.can_take(t, 100)  # 200 > 150 quota
    ledger.put(t, 100)
    assert ledger.can_take(t, 100)


# ---------------------------------------------------------------------------
# WeightedCreditBroker: FIFO handoff, aging
# ---------------------------------------------------------------------------

def _spawn_acquirer(broker, cost, tenant=None, cls=BULK):
    done = threading.Event()
    ok = []

    def run():
        ok.append(broker.acquire(cost, tenant, cls))
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return done, ok


def test_fifo_handoff_oversized_not_bypassed():
    """The serve-pool fairness fix: a clamped oversized waiter at the
    head of the plain FIFO is NOT bypassed by a later small request
    that would fit the remaining credits."""
    broker = WeightedCreditBroker(
        "t", 100, threading.Condition(), qos=None
    )
    assert broker.acquire(60)  # holder
    big_done, _ = _spawn_acquirer(broker, 1000)   # clamps to 100, waits
    time.sleep(0.05)
    small_done, _ = _spawn_acquirer(broker, 30)   # fits free=40, but FIFO
    time.sleep(0.1)
    assert not big_done.is_set()
    assert not small_done.is_set(), "small serve bypassed the FIFO head"
    broker.release(60)
    assert big_done.wait(2), "head waiter starved"
    assert not small_done.is_set()
    broker.release(100)
    assert small_done.wait(2)
    broker.release(30)
    assert broker.free == 100


def test_bulk_waiter_ages_ahead_of_fresh_interactive():
    """Anti-starvation aging: a bulk-class credit waiter older than
    qosAging is promoted and granted before a FRESH interactive
    waiter; without aging the interactive one wins."""
    qos = TenantRegistry(enabled=True)
    tb = qos.tenant("bulky", priority=BULK)
    ti = qos.tenant("snappy", priority=INTERACTIVE)
    for aging_ms, bulk_first in ((30, True), (60_000, False)):
        broker = WeightedCreditBroker(
            "t", 100, threading.Condition(), qos=qos, classed=True,
            aging_ms=aging_ms,
        )
        assert broker.acquire(100, tb)  # budget fully held
        bulk_done, _ = _spawn_acquirer(broker, 100, tb, BULK)
        time.sleep(0.08)  # > 30ms: the bulk waiter has aged
        int_done, _ = _spawn_acquirer(broker, 100, ti, INTERACTIVE)
        time.sleep(0.05)
        broker.release(100, tb)  # one grant's worth of credits
        first = bulk_done if bulk_first else int_done
        second = int_done if bulk_first else bulk_done
        assert first.wait(2), f"aging_ms={aging_ms}"
        time.sleep(0.05)
        assert not second.is_set(), f"aging_ms={aging_ms}"
        broker.release(100, tb if bulk_first else ti)
        assert second.wait(2)
        broker.stop()


def test_aged_oversized_waiter_accumulates_credits():
    """Classed mode: a clamped oversized bulk waiter short of raw
    credits becomes a BARRIER once aged — a cross-tenant stream of
    small acquisitions (which FIFO-within-(class,tenant) alone would
    let bypass forever) stops draining the credits it accumulates."""
    qos = TenantRegistry(enabled=True)
    big_t = qos.tenant("bigT", priority=BULK)
    small_t = qos.tenant("smallT", priority=BULK)
    broker = WeightedCreditBroker(
        "t", 100, threading.Condition(), qos=qos, classed=True,
        aging_ms=30,
    )
    stop = threading.Event()
    churned = [0]

    def churn():
        # small same-class, OTHER-tenant stream: acquire 30, hold
        # briefly, release — without the aged barrier this keeps free
        # below 100 forever
        while not stop.is_set():
            if broker.try_acquire(30, small_t, BULK):
                churned[0] += 1
                time.sleep(0.002)
                broker.release(30, small_t)
            else:
                time.sleep(0.002)

    th = threading.Thread(target=churn, daemon=True)
    th.start()
    time.sleep(0.03)
    assert churned[0] > 0
    done, ok = _spawn_acquirer(broker, 1000, big_t, BULK)  # clamps to 100
    assert done.wait(5), "aged oversized waiter starved by small stream"
    assert ok == [True]
    broker.release(100, big_t)
    stop.set()
    th.join(timeout=2)
    broker.stop()


def test_release_seq_bumps_on_release():
    """The lost-wakeup guard the reader's pump relies on: every
    release bumps the sequence a denied non-blocking acquirer compares
    across its deny-and-requeue window."""
    broker = WeightedCreditBroker(
        "t", 100, threading.Condition(), qos=None
    )
    s0 = broker.release_seq
    assert broker.acquire(100)
    assert broker.release_seq == s0
    broker.release(100)
    assert broker.release_seq == s0 + 1


def test_classed_queue_order_and_aging():
    cv = threading.Condition()
    q = ClassedTaskQueue(cv, classed=True, aging_ms=50)
    q.put("b1", BULK)
    q.put("b2", BULK)
    q.put("i1", INTERACTIVE)
    assert q.get() == "i1"          # interactive dequeues first
    time.sleep(0.08)                # b1 AND b2 age past 50ms
    q.put("i2", INTERACTIVE)
    assert q.get() == "b1"          # aged bulk outranks fresh interactive
    assert q.get() == "b2"
    assert q.get() == "i2"
    # unclassed = plain FIFO, and sentinels dequeue after real work
    q2 = ClassedTaskQueue(threading.Condition(), classed=False)
    q2.put("x", INTERACTIVE)
    q2.put_sentinel()
    q2.put("y", BULK)
    assert [q2.get(), q2.get(), q2.get()] == ["x", "y", None]


def test_lane_pool_reserve_for_interactive():
    pool = _LanePool(8, reserve=2)
    assert pool.try_borrow(8, cls=BULK) == 6   # reserve withheld
    assert pool.try_borrow(4, cls=BULK) == 0   # bulk side exhausted
    assert pool.try_borrow(4, cls=INTERACTIVE) == 2  # reserve served
    pool.release(8)
    assert pool.try_borrow(8, cls=INTERACTIVE) == 8  # interactive: all
    pool.release(8)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_over_quota_degrades_and_recovers():
    qos = TenantRegistry(enabled=True)
    t = qos.tenant("cap", max_bytes=1000)
    assert qos.admit(1, t, 800)
    assert not t.degraded
    t0 = time.monotonic()
    assert not qos.admit(2, t, 500, wait_s=0.05)  # queues, then degrades
    assert time.monotonic() - t0 >= 0.04, "did not queue before degrading"
    assert t.degraded
    assert t.registered_bytes == 1300
    qos.release_shuffle(1)  # back under quota: degraded clears
    assert not t.degraded
    assert t.registered_bytes == 500
    qos.release_shuffle(2)
    assert t.registered_bytes == 0


def test_admission_queued_commit_admitted_on_release():
    """A queued over-quota admit goes through WITHIN quota when an
    earlier shuffle releases during the wait."""
    qos = TenantRegistry(enabled=True)
    t = qos.tenant("cap2", max_bytes=1000)
    assert qos.admit(1, t, 900)
    results = []
    done = threading.Event()

    def admit():
        results.append(qos.admit(2, t, 500, wait_s=5.0))
        done.set()

    th = threading.Thread(target=admit, daemon=True)
    th.start()
    time.sleep(0.05)
    assert not done.is_set()
    qos.release_shuffle(1)
    assert done.wait(2)
    assert results == [True]
    assert not t.degraded


# ---------------------------------------------------------------------------
# tier: weighted hot-share protection + degrade
# ---------------------------------------------------------------------------

def _tier_entry(store, arena, shuffle_id, n_blocks=4, block=4096,
                seed=11):
    rng = np.random.default_rng(seed + shuffle_id)
    pattern = rng.integers(0, 256, n_blocks * block, dtype=np.uint8)
    mf = MappedFile(pattern.tobytes(), direct_write=False,
                    defer_map=True)
    spans = [(i * block, block) for i in range(n_blocks)]
    seg = store.adopt(mf, spans, n_blocks * block, shuffle_id, arena)
    return seg, pattern


def test_tier_share_protection_and_degrade():
    """An over-share tenant cannot demote an under-share tenant's hot
    blocks; a DEGRADED tenant is never promoted (cold serves)."""
    qos = TenantRegistry(enabled=True)
    ta = qos.tenant("tA", weight=1)
    tb = qos.tenant("tB", weight=1)
    qos.bind_shuffle(101, ta)
    qos.bind_shuffle(102, tb)
    block = 4096
    store = TieredBlockStore(hot_bytes=4 * block, qos=qos)
    arena = ArenaManager()
    seg_a, pat_a = _tier_entry(store, arena, 101)
    seg_b, pat_b = _tier_entry(store, arena, 102)
    # A fills the whole budget (work conservation: B idle) — warm then
    # touch each block so later evictions see consumed (touched) bytes
    for i in range(4):
        assert store.warm(seg_a.mkey, i * block, block) == 1
        seg_a.read(i * block, block)
    assert store.stats()["hot_bytes"] == 4 * block
    # B promotes two blocks: A is over its (now shared) 2-block share,
    # so B reclaims from A's LRU
    for i in range(2):
        assert store.warm(seg_b.mkey, i * block, block) == 1
    st = store._hot_by_tenant
    assert st.get("tB", 0) == 2 * block
    assert st.get("tA", 0) == 2 * block
    # A (at share) promotes another block (sub-range read → demand
    # promotion): B's at-share hot set is protected — A may only
    # displace its OWN blocks
    seg_a.read(0, block - 512)
    assert store._hot_by_tenant.get("tB", 0) == 2 * block
    assert store._hot_by_tenant.get("tA", 0) == 2 * block
    # degrade: a degraded tenant's promotions are denied outright
    ta.degraded = True
    d0 = _counter("qos_tier_denials_total", tenant="tA")
    assert store.warm(seg_a.mkey, 2 * block, block) == 0
    assert _counter("qos_tier_denials_total", tenant="tA") == d0 + 1
    # reads still serve, bit-exact, from the cold tier
    got = seg_a.read(2 * block, block)
    assert np.array_equal(
        np.asarray(got), pat_a[2 * block : 3 * block]
    )
    got = seg_b.read(0, block)
    assert np.array_equal(np.asarray(got), pat_b[:block])
    arena.release(seg_a.mkey)
    arena.release(seg_b.mkey)


# ---------------------------------------------------------------------------
# e2e: identity with QoS off, bit-exactness with QoS on
# ---------------------------------------------------------------------------

def _run_cluster_shuffle(extra_conf, port, n_execs=2, num_maps=4,
                         num_parts=4):
    net = LoopbackNetwork()
    conf_map = {"spark.shuffle.tpu.driverPort": port}
    conf_map.update(extra_conf or {})
    conf = TpuShuffleConf(conf_map)
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=port + 100 + i * 10, executor_id=str(i),
        )
        for i in range(n_execs)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n_execs for e in executors):
            break
        time.sleep(0.01)
    try:
        handle = driver.register_shuffle(
            7, num_maps, HashPartitioner(num_parts)
        )
        maps_by_host = defaultdict(list)
        for m in range(num_maps):
            ex = executors[m % n_execs]
            w = ex.get_writer(handle, m)
            w.write([(f"k{j % 17}", (m, j)) for j in range(200)])
            w.stop(True)
            maps_by_host[ex.local_smid].append(m)
        out = []
        for p in range(num_parts):
            r = executors[p % n_execs].get_reader(
                handle, p, p + 1, dict(maps_by_host)
            )
            out.extend(r.read())
        driver.unregister_shuffle(7)
        return sorted(out), handle, driver, executors
    finally:
        for m in executors + [driver]:
            m.stop()


def test_qos_disabled_is_identity():
    """qosEnabled=false (the default): no tenant machinery anywhere —
    node.qos None, no inflight broker, unclassed serve queue, plain
    ledger — and the shuffle output matches the expected records."""
    out, handle, driver, executors = _run_cluster_shuffle(
        {}, BASE_PORT
    )
    assert driver.qos is None
    assert handle.tenant == ""
    for m in executors + [driver]:
        assert m.node.qos is None
        assert m.qos_inflight_broker() is None
        assert m.qos_tenant_for(handle) is None
    expected = sorted(
        (f"k{j % 17}", (m, j)) for m in range(4) for j in range(200)
    )
    assert out == expected


def test_qos_on_single_tenant_bit_exact():
    """qosEnabled=true with one tenant: identical records to the
    qos-off run (work conservation — policy never changes bytes), and
    the tenant bookkeeping is live (binding, registered bytes)."""
    out_off, _h, _d, _e = _run_cluster_shuffle({}, BASE_PORT + 1000)
    GLOBAL_QOS.reset()
    out_on, handle, driver, _execs = _run_cluster_shuffle(
        {
            "spark.shuffle.tpu.qosEnabled": True,
            "spark.shuffle.tpu.tenant": "solo",
            "spark.shuffle.tpu.decodeThreads": 2,
        },
        BASE_PORT + 2000,
    )
    assert out_on == out_off
    assert handle.tenant == "solo"
    t = GLOBAL_QOS.tenant("solo")
    # unregister released the admitted bytes back to zero
    assert t.registered_bytes == 0
    assert not t.degraded
    assert _counter("qos_granted_bytes_total", pool="serve",
                    tenant="solo") > 0


def test_lock_debug_stress_with_brokers_active():
    """Two tenants' shuffles concurrently under lockDebug + QoS +
    metrics: zero rank violations with every broker lock hot (the
    PR 4 acceptance shape, rerun over the qos/ edges)."""
    out, _h, _d, _e = _run_cluster_shuffle(
        {
            "spark.shuffle.tpu.qosEnabled": True,
            "spark.shuffle.tpu.lockDebug": True,
            "spark.shuffle.tpu.metrics": True,
            "spark.shuffle.tpu.decodeThreads": 2,
            "spark.shuffle.tpu.qosTenantMaxBytes": "64k",
        },
        BASE_PORT + 3000,
    )
    assert len(out) == 800
    assert _counter("lock_rank_violations_total") == 0
    from sparkrdma_tpu.utils.dbglock import get_lock_factory

    get_lock_factory().enabled = False


def test_release_shuffle_without_known_tenant_still_returns_admits():
    """Regression: ``release_shuffle`` used to early-return when the
    shuffle's tenant could not be resolved, leaking the admit quota
    (the resource ledger's ``qos.admitted_bytes`` tickets) forever.
    An unresolvable tenant must still hand the admitted bytes back."""
    from sparkrdma_tpu.qos.registry import Tenant
    from sparkrdma_tpu.utils.ledger import get_resource_ledger

    led = get_resource_ledger()
    led.reset()
    led.enabled = True
    try:
        qos = TenantRegistry(enabled=True)
        # a tenant object the registry never saw: tenant_of_shuffle
        # resolution fails at release time
        stray = Tenant("ghost")
        assert qos.admit(7, stray, 4096)
        assert led.outstanding() == {"qos.admitted_bytes": 4096}
        qos.release_shuffle(7)
        assert led.outstanding() == {}
        assert led.double_releases() == 0
        qos.release_shuffle(7)  # duplicate clean (broadcast): no-op
        assert led.double_releases() == 0
    finally:
        led.enabled = False
        led.reset()
