"""Live metrics scrape endpoint (qos/http.py): bind an ephemeral
port, run a tenant-labeled shuffle, scrape /metrics over real HTTP,
parse the exposition, and verify clean shutdown leaks nothing into
the transport census."""

import json
import threading
import time
import urllib.request
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.qos.registry import GLOBAL_QOS
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.transport.node import transport_census

BASE_PORT = 31500


@pytest.fixture(autouse=True)
def registries():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_QOS.reset()
    yield
    GLOBAL_REGISTRY.enabled = prev
    GLOBAL_QOS.enabled = False
    GLOBAL_QOS.reset()


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=5) as resp:
        assert resp.status == 200
        return resp.read()


def _parse_prom(text: str) -> dict:
    """Minimal exposition parse: series string → float value."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, _sp, value = line.rpartition(" ")
        out[series] = float(value)
    return out


def test_scrape_endpoint_live_tenant_labels_and_clean_shutdown():
    census0 = transport_census()
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": BASE_PORT,
        "spark.shuffle.tpu.metricsHttpPort": 0,  # ephemeral bind
        "spark.shuffle.tpu.qosEnabled": True,
        "spark.shuffle.tpu.tenant": "scraped",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    # metricsHttpPort implies metrics: the registry is live
    assert GLOBAL_REGISTRY.enabled
    assert driver.metrics_http is not None
    port = driver.metrics_http.port
    assert port > 0
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=BASE_PORT + 100 + i * 10, executor_id=str(i),
        )
        for i in range(2)
    ]
    # in-process cluster: only the first manager wins the ephemeral
    # bind race... every manager binds its own ephemeral port, all live
    for e in executors:
        assert e.metrics_http is not None
        assert e.metrics_http.port not in (0, port)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    try:
        handle = driver.register_shuffle(3, 2, HashPartitioner(2))
        maps_by_host = defaultdict(list)
        for m in range(2):
            w = executors[m].get_writer(handle, m)
            w.write([(j % 7, j) for j in range(300)])
            w.stop(True)
            maps_by_host[executors[m].local_smid].append(m)
        records = []
        for p in range(2):
            r = executors[(p + 1) % 2].get_reader(
                handle, p, p + 1, dict(maps_by_host)
            )
            records.extend(r.read())
        assert len(records) == 600

        # live scrape MID-RUN (before any stop): text exposition
        url = driver.metrics_http.url("/metrics")
        series = _parse_prom(_get(url).decode("utf-8"))
        assert series, "empty exposition"
        tenant_series = [
            s for s in series if 'tenant="scraped"' in s
        ]
        assert tenant_series, (
            f"no tenant-labeled series in scrape: {sorted(series)[:20]}"
        )
        assert any(
            s.startswith("qos_granted_bytes_total") for s in tenant_series
        )
        # JSON snapshot + tenants view on the same endpoint
        snap = json.loads(_get(driver.metrics_http.url("/metrics.json")))
        assert {"counters", "gauges", "histograms"} <= set(snap)
        tenants = json.loads(_get(driver.metrics_http.url("/tenants")))
        assert tenants["enabled"]
        assert any(
            t["name"] == "scraped" for t in tenants["tenants"]
        )
        assert str(handle.shuffle_id) in json.dumps(tenants["shuffles"])
        # unknown path → 404, endpoint stays healthy after it
        with pytest.raises(urllib.error.HTTPError):
            _get(driver.metrics_http.url("/nope"))
        assert _get(url)
        driver.unregister_shuffle(3)
    finally:
        for m in executors + [driver]:
            m.stop()

    # clean shutdown: the port no longer answers and no serving thread
    # leaked (census + thread-name check)
    with pytest.raises(Exception):
        _get(f"http://127.0.0.1:{port}/metrics")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        leftover = [
            t.name for t in threading.enumerate()
            if t.name.startswith("metrics-http-")
        ]
        if not leftover:
            break
        time.sleep(0.05)
    assert not leftover, f"scrape threads leaked: {leftover}"
    census = transport_census()
    assert census["transport_threads"] <= census0["transport_threads"]
