"""Hypothesis edge-biased fuzz for the native record-plane kernels —
complements the seeded sweeps in test_memory.py/test_fuzz.py with
shrinkable counterexamples and int64-boundary biasing (the custom
fuzzers draw from modest ranges and would never propose INT64_MIN/MAX
or adversarial duplicate structure on their own)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

i64 = st.integers(min_value=-(2 ** 63), max_value=2 ** 63 - 1)


@st.composite
def sorted_runs(draw):
    """1-5 key-sorted runs with duplicate-heavy int64 keys (boundary
    values included) and matching 8-byte payload rows."""
    nruns = draw(st.integers(1, 5))
    pool = draw(st.lists(i64, min_size=1, max_size=6, unique=True))
    runs = []
    for _ in range(nruns):
        ks = sorted(
            draw(st.lists(st.sampled_from(pool), min_size=0, max_size=30))
        )
        keys = np.asarray(ks, np.int64)
        vals = np.arange(len(ks), dtype=np.int64) + draw(
            st.integers(0, 1 << 30)
        )
        runs.append((keys, vals))
    return runs


@settings(max_examples=200, deadline=None)
@given(sorted_runs())
def test_merge_runs_groups_hypothesis(runs):
    from sparkrdma_tpu.memory.staging import native_merge_runs_groups

    key_runs = [k for k, _ in runs]
    val_runs = [v for _, v in runs]
    res = native_merge_runs_groups(key_runs, val_runs)
    if res is None:  # native lib absent: covered by the numpy paths
        return
    uk, mv, offs = res
    n = sum(len(k) for k in key_runs)
    # oracle: for each distinct key ascending, run-0's rows then run-1's
    want_keys = sorted({int(k) for ks in key_runs for k in ks})
    assert list(uk) == want_keys
    assert offs[0] == 0 and offs[-1] == n == len(mv)
    for i, k in enumerate(want_keys):
        want_vals = [
            int(v)
            for ks, vs in runs
            for v in vs[ks == k]
        ]
        assert mv[offs[i]:offs[i + 1]].tolist() == want_vals, k


@settings(max_examples=200, deadline=None)
@given(st.lists(i64, min_size=0, max_size=200))
def test_radix_argsort_hypothesis(keys):
    from sparkrdma_tpu.memory.staging import native_radix_argsort

    arr = np.asarray(keys, np.int64)
    order = native_radix_argsort(arr)
    if order is None:
        return
    ref = np.argsort(arr, kind="stable")
    assert np.array_equal(order, ref)


@settings(max_examples=150, deadline=None)
@given(
    st.lists(
        st.lists(i64, min_size=0, max_size=40), min_size=1, max_size=5
    )
)
def test_kway_merge_hypothesis(raw_runs):
    from sparkrdma_tpu.memory.staging import native_kway_merge

    runs = [np.sort(np.asarray(r, np.int64)) for r in raw_runs]
    cat = (
        np.concatenate(runs) if runs else np.empty(0, np.int64)
    )
    offs = np.zeros(len(runs) + 1, np.int64)
    np.cumsum([len(r) for r in runs], out=offs[1:])
    order = native_kway_merge(np.ascontiguousarray(cat), offs)
    if order is None:
        return
    ref = np.argsort(cat, kind="stable")
    assert np.array_equal(order, ref)
