"""Striped multi-channel block transport (transport/stripe.py):
bit-exact sweeps across stripe counts and thresholds on BOTH backends,
scatter-gather on/off interop, serve-pool credit bounding, and the
reader-level striped fetch path."""

import threading
import time
from collections import defaultdict

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.arena import ArenaManager
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.transport.channel import FnCompletionListener
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.utils.types import BlockLocation

BASE_PORT = 25100

_PATTERN = (np.arange(6 << 20, dtype=np.uint32) % 251).astype(np.uint8)


def _conf(stripes, threshold, extra=None):
    d = {
        "spark.shuffle.tpu.transportNumStripes": stripes,
        "spark.shuffle.tpu.transportStripeThreshold": threshold,
    }
    d.update(extra or {})
    return TpuShuffleConf(d)


def _pair(netcls, port, conf):
    net = netcls()
    a = Node(("127.0.0.1", port), conf)
    b = Node(("127.0.0.1", port + 7), conf)
    net.register(a)
    net.register(b)
    arena = ArenaManager()
    seg = arena.register(_PATTERN, zero_copy_ok=True)
    b.register_block_store(seg.mkey, arena)
    return net, a, b, seg.mkey


def _teardown(net, a, b):
    a.stop()
    b.stop()
    net.unregister(a)
    net.unregister(b)


def _group_read(group, locs, timeout=30, on_progress=None):
    done = threading.Event()
    res = {}
    group.read_blocks(
        locs,
        FnCompletionListener(
            lambda blocks: (res.setdefault("blocks", blocks), done.set()),
            lambda e: (res.setdefault("error", e), done.set()),
        ),
        on_progress=on_progress,
    )
    assert done.wait(timeout), "group read hung"
    if "error" in res:
        raise res["error"]
    return res["blocks"]


def _as_np(blk):
    if isinstance(blk, np.ndarray):
        return blk
    return np.frombuffer(memoryview(blk), np.uint8)


@pytest.mark.parametrize("netcls,port", [
    (TcpNetwork, BASE_PORT),
    (LoopbackNetwork, BASE_PORT + 20),
])
@pytest.mark.parametrize("stripes,threshold", [
    (1, "128k"), (2, "128k"), (3, "64k"), (4, "256k"),
])
def test_striped_read_bit_exact_sweep(netcls, port, stripes, threshold):
    """Every (backend, stripe count, threshold) serves bit-identical
    payloads for a mixed small/large location batch — including
    exactly-at-threshold and threshold+1 edge sizes."""
    conf = _conf(stripes, threshold)
    net, a, b, mkey = _pair(netcls, port + stripes, conf)
    try:
        th = conf.transport_stripe_threshold
        locs = [
            BlockLocation(3, 100, mkey),          # tiny
            BlockLocation(103, th, mkey),         # == threshold: NOT striped
            BlockLocation(5, th + 1, mkey),       # barely striped
            BlockLocation(1 << 20, 3 << 20, mkey),  # bulk
            BlockLocation(0, 1, mkey),
        ]
        group = a.get_read_group(b.address, net.connect)
        blocks = _group_read(group, locs)
        assert len(blocks) == len(locs)
        for loc, blk in zip(locs, blocks):
            got = _as_np(blk)
            assert got.shape[0] == loc.length
            assert np.array_equal(
                got, _PATTERN[loc.address:loc.address + loc.length]
            ), f"corrupt block {loc} at stripes={stripes}"
        if stripes > 1:
            # the bulk blocks actually rode the striped path
            assert all(
                isinstance(blocks[i], np.ndarray)
                and not blocks[i].flags.writeable
                for i in (2, 3)
            )
    finally:
        _teardown(net, a, b)


def test_striped_matches_single_channel_and_tcp_matches_loopback():
    """The striped result is byte-identical to the single-channel
    result, and the TCP plane is byte-identical to loopback (the
    single-process tests exercise the same stripe/reassembly
    contract)."""
    locs_spec = [(11, 900_000), (950_000, 2 << 20), (7, 64)]
    results = {}
    for name, netcls, port, stripes in [
        ("tcp1", TcpNetwork, BASE_PORT + 40, 1),
        ("tcp4", TcpNetwork, BASE_PORT + 60, 4),
        ("loop4", LoopbackNetwork, BASE_PORT + 80, 4),
    ]:
        net, a, b, mkey = _pair(netcls, port, _conf(stripes, "128k"))
        try:
            group = a.get_read_group(b.address, net.connect)
            blocks = _group_read(
                group, [BlockLocation(o, n, mkey) for o, n in locs_spec]
            )
            results[name] = [bytes(_as_np(blk)) for blk in blocks]
        finally:
            _teardown(net, a, b)
    assert results["tcp4"] == results["tcp1"]
    assert results["loop4"] == results["tcp4"]


def test_scatter_gather_off_interop_bit_exact():
    """transportScatterGather=off restores the concat+sendall wire path
    with identical framing — the two endpoints interoperate and the
    payloads stay bit-exact."""
    conf = _conf(2, "128k", {
        "spark.shuffle.tpu.transportScatterGather": "off",
    })
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 100, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        locs = [BlockLocation(9, 2 << 20, mkey), BlockLocation(1, 50, mkey)]
        blocks = _group_read(group, locs)
        for loc, blk in zip(locs, blocks):
            assert np.array_equal(
                _as_np(blk), _PATTERN[loc.address:loc.address + loc.length]
            )
    finally:
        _teardown(net, a, b)


def test_progress_accounts_every_stripe_byte():
    """on_progress reports sum exactly to the requested byte total, in
    stripe-sized increments for striped blocks (the reader's in-flight
    window frees bytes as stripes land, not whole blocks)."""
    conf = _conf(4, "128k")
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 120, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        locs = [BlockLocation(0, 2 << 20, mkey), BlockLocation(5, 10, mkey)]
        prog = []
        _group_read(group, locs, on_progress=lambda n: prog.append(n))
        assert sum(prog) == sum(loc.length for loc in locs)
        # the 2 MiB block must have landed in more than one increment
        assert len([n for n in prog if n > 10]) > 1
    finally:
        _teardown(net, a, b)


def test_serve_pool_credits_bound_but_never_deadlock():
    """A credit budget far below the concurrent serve volume must
    throttle (credit waits observed) yet complete every read — a
    single serve larger than the whole budget clamps instead of
    wedging."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    prev_enabled = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    conf = _conf(2, "256k", {
        "spark.shuffle.tpu.transportServeThreads": 2,
        "spark.shuffle.tpu.transportServeCreditBytes": "1m",
    })
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 140, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        done = [threading.Event() for _ in range(6)]
        errors = []

        def issue(i):
            group.read_blocks(
                [BlockLocation(i * 100, 2 << 20, mkey)],
                FnCompletionListener(
                    lambda blocks, i=i: (
                        _check(blocks, i), done[i].set()
                    ),
                    lambda e, i=i: (errors.append(e), done[i].set()),
                ),
            )

        def _check(blocks, i):
            if not np.array_equal(
                _as_np(blocks[0]), _PATTERN[i * 100:i * 100 + (2 << 20)]
            ):
                errors.append(AssertionError(f"corrupt read {i}"))

        for i in range(6):
            issue(i)
        for ev in done:
            assert ev.wait(30), "serve-credit read hung"
        assert not errors, errors
    finally:
        _teardown(net, a, b)
        GLOBAL_REGISTRY.enabled = prev_enabled


def test_reader_striped_fetch_e2e_loopback():
    """Manager-level reduce over loopback with striping forced on:
    records come back exact and the stripe counters prove the striped
    path actually ran."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner

    prev_enabled = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    net = LoopbackNetwork()
    conf_d = {
        "spark.shuffle.tpu.driverPort": BASE_PORT + 160,
        "spark.shuffle.tpu.transportNumStripes": 3,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        # one fetch group may hold a whole multi-MB block
        "spark.shuffle.tpu.shuffleReadBlockSize": "8m",
        "spark.shuffle.tpu.maxAggBlock": "8m",
    }
    driver = TpuShuffleManager(
        TpuShuffleConf(conf_d), is_driver=True, network=net,
        port=BASE_PORT + 160, stage_to_device=False,
    )
    executors = [
        TpuShuffleManager(
            TpuShuffleConf(conf_d), is_driver=False, network=net,
            port=BASE_PORT + 170 + i * 3, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    try:
        stripes_before = GLOBAL_REGISTRY.counter(
            "transport_stripes_total").value
        part = HashPartitioner(2)
        handle = driver.register_shuffle(31, 2, part)
        maps_by_host = defaultdict(list)
        expected = {}
        for map_id in range(2):
            ex = executors[map_id]
            w = ex.get_writer(handle, map_id)
            recs = [
                (f"m{map_id}k{j}", bytes([j % 251]) * 40_000)
                for j in range(40)
            ]
            expected.update(recs)
            w.write(recs)
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)
        got = {}
        for i, ex in enumerate(executors):
            reader = ex.get_reader(handle, i, i + 1, dict(maps_by_host))
            for k, v in reader.read():
                got[k] = bytes(memoryview(v)) if not isinstance(v, bytes) \
                    else v
            assert reader.metrics.remote_blocks > 0
        assert got == expected
        stripes_after = GLOBAL_REGISTRY.counter(
            "transport_stripes_total").value
        assert stripes_after > stripes_before, (
            "striped path never ran — threshold/grouping regression?"
        )
    finally:
        for m in executors + [driver]:
            m.stop()
        GLOBAL_REGISTRY.enabled = prev_enabled


def test_killed_data_channel_fails_group_promptly():
    """Stopping one data lane mid-striped-read surfaces a clean
    TransportError on the whole group read (never a hang): each lane's
    _fail_outstanding covers its stripes and the combiner fans the
    first error out exactly once."""
    conf = _conf(2, "128k")
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 200, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        # pre-create the data lanes so the victim exists before the read
        lanes = group.data_channels()
        done = threading.Event()
        res = {}
        group.read_blocks(
            [BlockLocation(0, 4 << 20, mkey)],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
        )
        lanes[0].stop()
        assert done.wait(15), "striped read hung after lane death"
        # either the whole payload raced home first, or the group
        # failed cleanly — both are within the fetch contract
        if "ok" in res:
            assert np.array_equal(_as_np(res["ok"][0]),
                                  _PATTERN[:4 << 20])
        else:
            assert isinstance(res["error"], Exception)
    finally:
        _teardown(net, a, b)


def test_peer_death_mid_response_body_fails_listener():
    """A peer that sends the OP_READ_RESP header then dies mid-body
    must fail THAT read's listener promptly: the entry already left
    _reads when the body receive started, so _fail_outstanding can't
    cover it — the structured receive has to."""
    import socket as socket_mod
    import struct

    from sparkrdma_tpu.transport import tcp as tcp_mod

    port = BASE_PORT + 260
    srv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    srv.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port + 7))
    srv.listen(4)

    def evil_server():
        while True:
            try:
                sock, _addr = srv.accept()
            except OSError:
                return
            try:
                sock.recv(tcp_mod._HELLO.size)       # hello
                sock.sendall(b"\x01")                # ack
                # one READ_REQ frame: header + req payload
                hdr = sock.recv(tcp_mod._HDR.size)
                _op, ln = tcp_mod._HDR.unpack(hdr)
                req = b""
                while len(req) < ln:
                    req += sock.recv(ln - len(req))
                (req_id,) = struct.unpack_from("<Q", req, 0)
                # claim a full response, deliver the resp header +
                # half a block, then die (no goodbye)
                sock.sendall(tcp_mod._HDR.pack(
                    tcp_mod.OP_READ_RESP,
                    tcp_mod._RESP_HDR.size + tcp_mod._LEN.size + 1000,
                ))
                sock.sendall(tcp_mod._RESP_HDR.pack(req_id, 0))
                sock.sendall(tcp_mod._LEN.pack(1000) + b"x" * 500)
                sock.shutdown(socket_mod.SHUT_RDWR)
            except OSError:
                pass
            finally:
                sock.close()

    t = threading.Thread(target=evil_server, daemon=True)
    t.start()
    net = TcpNetwork()
    a = Node(("127.0.0.1", port), _conf(1, "128k"))
    net.register(a)
    try:
        group = a.get_read_group(("127.0.0.1", port + 7), net.connect)
        done = threading.Event()
        res = {}
        group.read_blocks(
            [BlockLocation(0, 1000, 1)],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
        )
        assert done.wait(10), (
            "listener stranded after peer death mid-body"
        )
        assert "error" in res
    finally:
        a.stop()
        net.unregister(a)
        srv.close()


def test_malformed_read_request_keeps_channel_alive():
    """A READ_REQ whose count field overruns the payload must get a
    scoped status=1 reply (or be dropped when even the req_id is
    garbage) — never kill the serving channel and its other reads."""
    import struct

    from sparkrdma_tpu.transport import tcp as tcp_mod
    from sparkrdma_tpu.transport.channel import ChannelType

    conf = _conf(1, "128k")
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 280, conf)
    try:
        ch = a.get_channel(
            b.address, ChannelType.READ_REQUESTOR, net.connect
        )
        # hand-craft a request claiming 5 locations but carrying none
        bogus = struct.pack("<QI", 999, 5)
        ch._send_msg(tcp_mod.OP_READ_REQ, (bogus,))
        # and one with an unparseable header
        ch._send_msg(tcp_mod.OP_READ_REQ, (b"\x01",))
        time.sleep(0.2)
        # the channel still serves a real read afterwards
        done = threading.Event()
        res = {}
        ch.read_blocks(
            [BlockLocation(0, 4096, mkey)],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
        )
        assert done.wait(10), "read after malformed request hung"
        assert "ok" in res, res.get("error")
        assert np.array_equal(_as_np(res["ok"][0]), _PATTERN[:4096])
    finally:
        _teardown(net, a, b)


def test_group_read_failure_converts_to_fetch_failed():
    """Reader-level: a read group whose peer died surfaces as
    FetchFailedError (stage-retriable), not a hang."""
    from sparkrdma_tpu.shuffle.reader import FetchFailedError  # noqa: F401

    conf = _conf(2, "128k")
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 220, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        b.stop()  # peer gone: outstanding + future reads must fail
        t0 = time.monotonic()
        done = threading.Event()
        res = {}
        try:
            group.read_blocks(
                [BlockLocation(0, 2 << 20, mkey)],
                FnCompletionListener(
                    lambda blocks: (res.setdefault("ok", blocks),
                                    done.set()),
                    lambda e: (res.setdefault("error", e), done.set()),
                ),
            )
        except Exception as e:
            res["error"] = e
            done.set()
        assert done.wait(15), "read against dead peer hung"
        assert "error" in res
        assert time.monotonic() - t0 < 15
    finally:
        a.stop()
        net.unregister(a)
        net.unregister(b)


def test_failed_striped_read_with_raising_listener_keeps_lanes_balanced():
    """Regression for the lane-token one-shot guard: a striped read
    that FAILS (unknown mkey at the server) whose ``on_failure``
    callback itself raises must still return every borrowed lane token
    exactly once — the pool refills and the resource ledger shows no
    outstanding ``node.lane_tokens`` and no double release."""
    from sparkrdma_tpu.utils.ledger import get_resource_ledger

    led = get_resource_ledger()
    led.reset()
    led.enabled = True
    conf = _conf(2, "64k")
    net, a, b, mkey = _pair(TcpNetwork, BASE_PORT + 320, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        pool = a.lane_pool
        free0 = pool._free
        done = threading.Event()

        def angry_failure(e):
            done.set()
            raise RuntimeError("listener exploded") from e

        group.read_blocks(
            [BlockLocation(0, 1 << 20, mkey + 4077)],  # bad mkey
            FnCompletionListener(
                lambda blocks: done.set(), angry_failure
            ),
        )
        assert done.wait(15), "failed striped read hung"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if (pool._free == free0
                    and not led.outstanding().get("node.lane_tokens")):
                break
            time.sleep(0.02)
        assert pool._free == free0, (pool._free, free0)
        assert not led.outstanding().get("node.lane_tokens"), \
            led.leak_report()
        assert led.double_releases() == 0
    finally:
        _teardown(net, a, b)
        led.enabled = False
        led.reset()


def test_serve_pool_queued_task_cancelled_at_stop_holds_no_credits():
    """Regression for the serve-credit lifecycle: tasks still QUEUED
    when the pool stops never acquired credits, so abandoning them
    must leave zero ``serve.credit_bytes`` outstanding — and the one
    in-flight task's deferred release still settles cleanly."""
    from sparkrdma_tpu.transport.node import _ServePool
    from sparkrdma_tpu.utils.ledger import get_resource_ledger

    led = get_resource_ledger()
    led.reset()
    led.enabled = True
    try:
        pool = _ServePool("t", workers=1, credit_bytes=1 << 16)
        started, unblock = threading.Event(), threading.Event()

        def blocker():
            started.set()
            unblock.wait(10)

        pool.submit(blocker, (), cost=1024)
        assert started.wait(5), "serve worker never picked up the task"
        for _ in range(4):  # queued behind the single busy worker
            pool.submit(lambda: None, (), cost=1024)
        pool.stop()  # abandons the queued serves
        unblock.set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if not led.outstanding().get("serve.credit_bytes"):
                break
            time.sleep(0.02)
        assert not led.outstanding().get("serve.credit_bytes"), \
            led.leak_report()
        assert led.double_releases() == 0
    finally:
        led.enabled = False
        led.reset()
