"""Tier-1 wrapper + unit fixtures for the lifecycle state-machine gate
(tools/statecheck.py): the real tree must be clean with the full
machine census discovered, and seeded violations must each produce
exactly their SC finding."""

import importlib.util
import pathlib
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_statecheck():
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_statecheck", REPO / "tools" / "statecheck.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _analyze_src(tmp_path, src: str, name="fixture.py"):
    sc = _load_statecheck()
    body = textwrap.dedent(src)
    compile(body, name, "exec")  # a broken fixture must not pass as clean
    f = tmp_path / name
    f.write_text(body)
    return sc.analyze([f], root=tmp_path)


def _codes(findings):
    return sorted(code for _rel, _line, code, _msg in findings)


# a well-formed machine the violation fixtures perturb: three states,
# one terminal, a linear a -> b -> c table, the mixin-shaped helper
BASE = """\
    class C:
        MACHINE = "fix.c"
        STATES = ("a", "b", "c")
        INITIAL = "a"
        TERMINAL = ("c",)
        TRANSITIONS = {"a": ("b",), "b": ("c",)}

        def __init__(self):
            self._state = "a"  # state: fix.c

        def _transition(self, to, frm=None):
            self._state = to

        def go(self):
            self._transition("b", frm="a")
"""


# -- tier-1: the real tree ----------------------------------------------------


def test_library_is_statecheck_clean():
    sc = _load_statecheck()
    findings = sc.analyze([REPO / "sparkrdma_tpu"])
    assert not findings, "\n".join(
        f"{rel}:{line}: {code} {msg}" for rel, line, code, msg in findings
    )


def test_library_machine_census_discovered():
    """Clean AND nonempty: the analyzer actually discovered the
    declared machine population (a discovery regression would pass
    vacuously) — the inventory the README documents is >= 8 complete
    machines with a real table and real call sites behind them."""
    sc = _load_statecheck()
    an = sc.Analyzer()
    an.analyze_paths([REPO / "sparkrdma_tpu"])
    machines = [m for m in an.machines if m.complete]
    assert len(machines) >= 8, sorted(m.name for m in machines)
    edges = sum(
        len(dsts) for m in machines for dsts in m.transitions.values()
    )
    assert edges >= 40, edges
    assert an.transition_sites >= 20, an.transition_sites
    # every complete machine's seed token is its declared INITIAL
    for m in machines:
        assert m.initial in m.states, (m.name, m.initial)
        assert set(m.terminal) <= set(m.states), m.name


def test_base_fixture_is_clean(tmp_path):
    assert _analyze_src(tmp_path, BASE) == []


def test_runtime_module_is_skipped(tmp_path):
    """utils/statemachine.py is the blessed writer (and its docstrings
    hold grammar examples): a file by that name is never scanned."""
    findings = _analyze_src(tmp_path, BASE + """\

        def poke(c):
            c._state = "b"
    """, name="statemachine.py")
    assert findings == []


# -- SC01: raw state writes ---------------------------------------------------


def test_sc01_raw_write_outside_helper(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        class User:
            def poke(self, c):
                c._state = "b"
    """)
    assert _codes(findings) == ["SC01"], findings
    assert "raw write" in findings[0][3]


def test_sc01_self_write_in_plain_method(tmp_path):
    findings = _analyze_src(tmp_path, BASE.replace(
        '        def go(self):\n'
        '            self._transition("b", frm="a")',
        '        def go(self):\n'
        '            self._state = "b"',
    ))
    assert _codes(findings) == ["SC01"], findings


def test_sc01_seeding_line_and_helper_are_exempt(tmp_path):
    # BASE itself writes _state in __init__ (annotated) and in
    # _transition (the helper) — both blessed
    assert _analyze_src(tmp_path, BASE) == []


# -- SC02: undeclared transitions ---------------------------------------------


def test_sc02_transition_to_unknown_state(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def zap(c):
            c._transition("vanished")
    """)
    assert _codes(findings) == ["SC02"], findings
    assert "undeclared state" in findings[0][3]


def test_sc02_missing_edge_with_frm(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def skip(c):
            c._transition("c", frm="a")
    """)
    assert _codes(findings) == ["SC02"], findings
    assert "not in the declared table" in findings[0][3]


def test_sc02_no_edge_into_dest_without_frm(tmp_path):
    src = BASE.replace(
        'STATES = ("a", "b", "c")', 'STATES = ("a", "b", "c", "orphan")'
    ) + """\

        def strand(c):
            c._transition("orphan")
    """
    findings = _analyze_src(tmp_path, src)
    assert _codes(findings) == ["SC02"], findings
    assert "no declared edge into" in findings[0][3]


def test_sc02_seed_disagrees_with_initial(tmp_path):
    findings = _analyze_src(tmp_path, BASE.replace(
        'self._state = "a"  # state: fix.c',
        'self._state = "b"  # state: fix.c',
    ))
    assert _codes(findings) == ["SC02"], findings


def test_sc02_dynamic_arguments_are_runtime_territory(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def relay(c, nxt):
            c._transition(nxt)
    """)
    assert findings == []


def test_self_edge_is_a_legal_noop(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def reassert(c):
            c._transition("a", frm="a")
    """)
    assert findings == []


# -- SC03: unguarded branch reads ---------------------------------------------

GUARDED = BASE.replace(
    '# state: fix.c', '# state: fix.c guarded-by: _lock'
).replace(
    'def __init__(self):',
    'def __init__(self):\n'
    '            import threading\n'
    '            self._lock = threading.Lock()',
)


def test_sc03_branch_read_without_guard(tmp_path):
    findings = _analyze_src(tmp_path, GUARDED.replace(
        '        def go(self):\n'
        '            self._transition("b", frm="a")',
        '        def go(self):\n'
        '            if self._state == "a":\n'
        '                self._transition("b", frm="a")',
    ))
    assert _codes(findings) == ["SC03"], findings
    assert "without holding its declared guard" in findings[0][3]


def test_sc03_read_under_the_guard_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, GUARDED.replace(
        '        def go(self):\n'
        '            self._transition("b", frm="a")',
        '        def go(self):\n'
        '            with self._lock:\n'
        '                if self._state == "a":\n'
        '                    self._transition("b", frm="a")',
    ))
    assert findings == []


def test_sc03_external_owner_guard(tmp_path):
    findings = _analyze_src(tmp_path, """\
        import threading


        class Ticket:
            MACHINE = "fix.tkt"
            STATES = ("queued", "done")
            INITIAL = "queued"
            TERMINAL = ("done",)
            TRANSITIONS = {"queued": ("done",)}

            def __init__(self):
                self._state = "queued"  # state: fix.tkt guarded-by: Pool._cv

            def _transition(self, to, frm=None):
                self._state = to


        class Pool:
            def __init__(self):
                self._cv = threading.Condition()

            def scan(self, t):
                if t._state == "queued":
                    return t

            def scan_locked(self, t):
                with self._cv:
                    if t._state == "queued":
                        return t
    """)
    assert _codes(findings) == ["SC03"], findings
    # only the unlocked scan() read fires, not scan_locked()
    assert findings[0][1] == 23, findings


# -- SC04: terminal escapes ---------------------------------------------------


def test_sc04_table_edge_out_of_terminal(tmp_path):
    findings = _analyze_src(tmp_path, BASE.replace(
        'TRANSITIONS = {"a": ("b",), "b": ("c",)}',
        'TRANSITIONS = {"a": ("b",), "b": ("c",), "c": ("a",)}',
    ))
    assert _codes(findings) == ["SC04"], findings
    assert "terminal" in findings[0][3]


def test_sc04_call_site_frm_terminal(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def reopen(c):
            c._transition("a", frm="c")
    """)
    assert _codes(findings) == ["SC04"], findings
    assert "out of terminal" in findings[0][3]


def test_sc04_lexical_use_after_terminal(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def finish(c):
            c._transition("c", frm="b")
            c._transition("b")
    """)
    assert _codes(findings) == ["SC04"], findings
    assert "same path" in findings[0][3]


def test_sc04_rebound_receiver_resets_the_path(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def recycle(mk):
            c = mk()
            c._transition("c", frm="b")
            c = mk()
            c._transition("b")
    """)
    assert findings == []


def test_sc04_branches_are_separate_paths(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        def either(c, stop):
            if stop:
                c._transition("c", frm="b")
            else:
                c._transition("b")
    """)
    assert findings == []


# -- SC05: undeclared / inconsistent machines ---------------------------------


def test_sc05_annotation_without_a_table(tmp_path):
    findings = _analyze_src(tmp_path, """\
        class Bare:
            def __init__(self):
                self._state = "new"  # state: fix.bare
    """)
    assert _codes(findings) == ["SC05"], findings


def test_sc05_machine_name_disagrees(tmp_path):
    findings = _analyze_src(tmp_path, BASE.replace(
        'MACHINE = "fix.c"', 'MACHINE = "fix.other"'
    ))
    assert "SC05" in _codes(findings), findings


def test_sc05_transition_token_outside_states(tmp_path):
    findings = _analyze_src(tmp_path, BASE.replace(
        'TRANSITIONS = {"a": ("b",), "b": ("c",)}',
        'TRANSITIONS = {"a": ("b",), "b": ("zz",)}',
    ))
    assert "SC05" in _codes(findings), findings


# -- suppression: code-scoped noqa --------------------------------------------


def test_noqa_silences_exactly_its_code(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        class User:
            def poke(self, c):
                c._state = "b"  # noqa: SC01 deliberate test write
    """)
    assert findings == []


def test_noqa_for_another_code_does_not_silence(tmp_path):
    findings = _analyze_src(tmp_path, BASE + """\

        class User:
            def poke(self, c):
                c._state = "b"  # noqa: SC03 wrong code
    """)
    assert _codes(findings) == ["SC01"], findings
