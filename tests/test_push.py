"""Push-based merged shuffle (shuffle/push.py): bit-exactness sweep
across transports/decode/skew, merger-death chaos (clean pull
fallback, zero stage retries), per-map dedup, and the pushEnabled=off
reader-plan pin."""

import itertools
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle import reader as reader_mod
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.utils.statemachine import shake_confs_from_env

# fresh base per cluster: clear of test_tcp (41000), test_shuffle_e2e
# (37000/38000), the conftest ProcessCluster range (24200+), and the
# bench port bases (23xxx/25200)
_PORTS = itertools.count(39300, 200)

NUM_MAPS, NUM_PARTS, RECORDS = 4, 6, 40


def _counters():
    """{(name, ((label, value), ...)): count} snapshot of the global
    registry — counters are cumulative, so tests diff two snapshots."""
    out = {}
    for c in GLOBAL_REGISTRY.snapshot()["counters"]:
        out[(c["name"], tuple(sorted(c["labels"].items())))] = c["value"]
    return out


def _delta(before, after, name, **labels):
    key = (name, tuple(sorted(labels.items())))
    return after.get(key, 0) - before.get(key, 0)


def _make_cluster(transport, conf_extra):
    """Driver + executors on a fresh port base.  Loopback shares one
    in-memory network (3 executors); the tcp variants give every
    manager its OWN TcpNetwork — real sockets, nothing shared."""
    base = next(_PORTS)
    confd = {
        "spark.shuffle.tpu.metrics": True,
        "spark.shuffle.tpu.driverPort": base,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "10s",
        "spark.shuffle.tpu.connectTimeout": "5s",
    }
    # make chaos-shake: SCHED_SHAKE=<seed> reruns every push drill
    # under the schedule shaker + state validator
    confd.update(shake_confs_from_env())
    confd.update(conf_extra)
    if transport == "loopback":
        net = LoopbackNetwork()
        conf = TpuShuffleConf(confd)
        driver = TpuShuffleManager(conf, is_driver=True, network=net)
        executors = [
            TpuShuffleManager(
                conf, is_driver=False, network=net,
                port=base + 100 + i * 10, executor_id=str(i),
            )
            for i in range(3)
        ]
    else:
        if transport == "tcp-threaded":
            confd["spark.shuffle.tpu.transportAsyncDispatcher"] = False
        driver = TpuShuffleManager(
            TpuShuffleConf(confd), is_driver=True, network=TcpNetwork(),
            port=base, stage_to_device=False,
        )
        executors = [
            TpuShuffleManager(
                TpuShuffleConf(confd), is_driver=False, network=TcpNetwork(),
                port=base + 100 + i * 10, executor_id=str(i),
                stage_to_device=False,
            )
            for i in range(2)
        ]
    n = len(executors)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n for e in executors):
            break
        time.sleep(0.01)
    return driver, executors


def _run_job(driver, executors, shuffle_id=0):
    """Write NUM_MAPS maps round-robin, read every partition round-robin.
    Returns ({key: sorted values}, expected dict of the same shape)."""
    part = HashPartitioner(NUM_PARTS)
    handle = driver.register_shuffle(shuffle_id, NUM_MAPS, part)
    records_per_map = [
        [(f"k{j}", (m, j)) for j in range(RECORDS)] for m in range(NUM_MAPS)
    ]
    maps_by_host = defaultdict(list)
    for map_id, records in enumerate(records_per_map):
        ex = executors[map_id % len(executors)]
        w = ex.get_writer(handle, map_id)
        w.write(records)
        w.stop(True)
        maps_by_host[ex.local_smid].append(map_id)
    got = {}
    for pid in range(NUM_PARTS):
        rd = executors[pid % len(executors)].get_reader(
            handle, pid, pid + 1, dict(maps_by_host))
        for k, v in rd.read():
            got.setdefault(k, []).append(v)
    expected = defaultdict(list)
    for recs in records_per_map:
        for k, v in recs:
            expected[k].append(v)
    return (
        {k: sorted(v) for k, v in got.items()},
        {k: sorted(v) for k, v in expected.items()},
    )


def _run_cluster(transport, conf_extra, shuffle_id=0):
    driver, executors = _make_cluster(transport, conf_extra)
    try:
        return _run_job(driver, executors, shuffle_id)
    finally:
        for m in executors + [driver]:
            m.stop()
        # under stateDebug/schedShake every lifecycle transition was
        # table-validated; a drill must never attempt an illegal one
        illegal = [
            (c["labels"], c["value"])
            for c in GLOBAL_REGISTRY.snapshot()["counters"]
            if c["name"] == "state_transitions_illegal_total"
            and c["value"] > 0
        ]
        assert not illegal, illegal


# -- bit-exactness sweep --------------------------------------------------

SWEEP = [
    (t, dt, skew)
    for t in ("loopback", "tcp-threaded", "tcp-async")
    for dt in (0, 4)
    for skew in (False, True)
]


@pytest.mark.parametrize(
    "transport,decode_threads,skew", SWEEP,
    ids=[f"{t}-dt{d}-{'skew' if s else 'noskew'}" for t, d, s in SWEEP])
def test_push_bit_exact_sweep(transport, decode_threads, skew):
    """Push mode returns exactly the pull answer on every transport ×
    decodeThreads × skew combination, and the merge plane actually
    engaged (this is a push run, not a silent pull fallback)."""
    extra = {
        "spark.shuffle.tpu.pushEnabled": True,
        "spark.shuffle.tpu.decodeThreads": decode_threads,
    }
    if skew:
        extra["spark.shuffle.tpu.skewEnabled"] = True
        extra["spark.shuffle.tpu.skewSplitThreshold"] = 4096
    before = _counters()
    got, expected = _run_cluster(transport, extra)
    after = _counters()
    assert got == expected
    assert _delta(before, after, "push_sub_blocks_total") > 0
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="merge_status") > 0
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="push") > 0
    assert _delta(before, after, "shuffle_fetch_failures_total") == 0


def test_push_vs_pull_same_answer_loopback():
    """Direct A/B: the same job with push on and push off produces the
    identical {key: sorted values} dict."""
    pull, expected = _run_cluster("loopback", {})
    push, _ = _run_cluster(
        "loopback", {"spark.shuffle.tpu.pushEnabled": True}, shuffle_id=1)
    assert pull == expected
    assert push == pull


# -- chaos: merger death & lossy merge plane ------------------------------

@pytest.fixture()
def clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def test_merger_dead_falls_back_to_pull(clean_faults):
    """Every merge-status query fails (dead merger drill): the stage
    completes bit-exact through the unchanged pull path with ZERO
    fetch failures — push is best-effort, never a stage retry."""
    before = _counters()
    got, expected = _run_cluster("loopback", {
        "spark.shuffle.tpu.pushEnabled": True,
        "spark.shuffle.tpu.faultInject": "merge_status:nth=1",
    })
    after = _counters()
    assert got == expected
    assert _delta(before, after, "push_merge_query_failures_total") > 0
    assert _delta(before, after, "shuffle_fetch_failures_total") == 0
    # nothing merged was served — the whole read went over pull
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="push") == 0


def test_lossy_merge_plane_pulls_stragglers(clean_faults):
    """Half the pushed sub-blocks are dropped at the merger rx: the
    reader serves merged coverage where it exists and pulls the
    unmerged stragglers — still bit-exact, still zero failures."""
    before = _counters()
    got, expected = _run_cluster("loopback", {
        "spark.shuffle.tpu.pushEnabled": True,
        "spark.shuffle.tpu.faultInject": "push_merge:nth=2;seed=7",
    })
    after = _counters()
    assert got == expected
    assert _delta(before, after, "push_drops_total", reason="fault") > 0
    assert _delta(before, after, "shuffle_fetch_failures_total") == 0
    # both planes carried data: merged spans AND straggler pulls
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="push") > 0
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="pull") > 0


# -- dedup under retried maps ---------------------------------------------

def test_merger_dedups_retried_map():
    """A retried map re-pushing its partition merges ONCE: the second
    arrival drops as a dup and provenance lists the map a single time."""
    driver, executors = _make_cluster("loopback", {
        "spark.shuffle.tpu.pushEnabled": True,
    })
    try:
        merger = executors[0].push_merger
        before = _counters()
        merger.on_sub_block(99, 5, 0, 6, 0, b"abcdef")
        merger.on_sub_block(99, 5, 0, 6, 0, b"abcdef")  # the retry
        after = _counters()
        assert _delta(before, after, "push_drops_total", reason="dup") == 1
        [(rid, mkey, length, prov)] = merger.merge_status(99, [0])
        assert rid == 0 and mkey != 0 and length == 6
        assert [row[0] for row in prov] == [5]  # map 5 exactly once
    finally:
        for m in executors + [driver]:
            m.stop()


# -- pushEnabled=off: the reader plan is untouched ------------------------

def test_push_off_reader_plan_identical(monkeypatch):
    """With pushEnabled=off (the default) the reader issues exactly
    the pre-push location plan — every remote (map, reduce) pair,
    nothing more — and never touches the merge plane."""
    recorded = []
    orig = reader_mod.ShuffleReader._query_locations

    def spy(self, host, pairs, on_ok):
        recorded.append((host, tuple(sorted(pairs))))
        return orig(self, host, pairs, on_ok)

    monkeypatch.setattr(reader_mod.ShuffleReader, "_query_locations", spy)

    driver, executors = _make_cluster("loopback", {})
    before = _counters()
    try:
        part = HashPartitioner(NUM_PARTS)
        handle = driver.register_shuffle(0, NUM_MAPS, part)
        maps_by_host = defaultdict(list)
        for map_id in range(NUM_MAPS):
            ex = executors[map_id % len(executors)]
            w = ex.get_writer(handle, map_id)
            w.write([(f"k{j}", (map_id, j)) for j in range(RECORDS)])
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)
        expected_calls = []
        for pid in range(NUM_PARTS):
            ex = executors[pid % len(executors)]
            rd = ex.get_reader(handle, pid, pid + 1, dict(maps_by_host))
            n = sum(1 for _ in rd.read())
            assert n > 0
            for host, mids in maps_by_host.items():
                if host == ex.local_smid:
                    continue  # local blocks short-circuit, never queried
                expected_calls.append(
                    (host, tuple(sorted((mid, pid) for mid in mids))))
    finally:
        for m in executors + [driver]:
            m.stop()
    after = _counters()
    plan_key = lambda c: (c[0].host, c[0].port, c[1])  # noqa: E731
    assert sorted(recorded, key=plan_key) == \
        sorted(expected_calls, key=plan_key)
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="push") == 0
    assert _delta(before, after,
                  "shuffle_fetch_rpcs_total", mode="merge_status") == 0
    assert _delta(before, after, "push_sub_blocks_total") == 0
