"""Schema-driven frame fuzz: truncated/mutated/bit-flipped frames fed
into ``decode_msg`` and BOTH engines' live receive machines under
``wireDebug``.  Everything must fail clean — structured
WireFormatError/TransportError, one-frame (or one-channel) blast
radius, healthy node afterward, zero ledger leaks, never a hang."""

import random
import socket
import struct
import threading

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY, counter
from sparkrdma_tpu.rpc.messages import (
    CleanShuffleMsg,
    FetchMapStatusFailedMsg,
    FetchMapStatusMsg,
    FetchMergeStatusMsg,
    HeartbeatMsg,
    HelloMsg,
    MergeStatusResponseMsg,
    PrefetchHintMsg,
    PushSubBlockMsg,
    WireFormatError,
    decode_msg,
)
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.transport import tcp as wire
from sparkrdma_tpu.transport.channel import (
    ChannelType,
    FnCompletionListener,
    TransportError,
)
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.utils import wiredbg
from sparkrdma_tpu.utils.ledger import get_resource_ledger
from sparkrdma_tpu.utils.types import (
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
)

BASE_PORT = 26200


def _smid(i):
    return ShuffleManagerId(
        f"host{i}", 9000 + i, BlockManagerId(str(i), f"host{i}", 7000 + i)
    )


def _corpus():
    """Valid frames across fixed, variable-length, and nested layouts."""
    return [
        m.encode()
        for m in (
            HelloMsg(_smid(1), channel_port=4242),
            HeartbeatMsg(_smid(2), seq=7, is_ack=True),
            CleanShuffleMsg(3),
            FetchMapStatusFailedMsg(5, reason="lost executor"),
            FetchMapStatusMsg(
                _smid(3), _smid(4), 1, 9, block_ids=[(0, 1), (2, 3)]
            ),
            PrefetchHintMsg(2, locations=[BlockLocation(0, 64, 5)]),
            # push-based merged shuffle (wire v3, types 13-15)
            PushSubBlockMsg(_smid(5), 1, 2, 3, 128, 64, b"\x5a" * 64),
            FetchMergeStatusMsg(_smid(6), 4, 17, (0, 3, 9)),
            MergeStatusResponseMsg(
                17, 2, 0, 3, 8, 2048, ((0, 0, 1024), (1, 1024, 1024))
            ),
        )
    ]


def _mutants(rng):
    """≥200 hostile frames: truncations, bit flips, byte substitutions,
    header lies, raw garbage."""
    muts = []
    for f in _corpus():
        L = len(f)
        for cut in sorted({0, 1, 3, 4, 7, L // 2, L - 1}):
            if cut < L:
                muts.append(f[:cut])
        for _ in range(10):
            b = bytearray(f)
            b[rng.randrange(L)] ^= 1 << rng.randrange(8)
            muts.append(bytes(b))
        for _ in range(8):
            b = bytearray(f)
            b[rng.randrange(L)] = rng.randrange(256)
            muts.append(bytes(b))
        # length-field lie and unknown-type lie
        muts.append(struct.pack("<i", L + 99) + f[4:])
        muts.append(f[:4] + struct.pack("<i", 99) + f[8:])
    for _ in range(40):
        muts.append(bytes(rng.randrange(256) for _ in range(
            rng.randrange(0, 64)
        )))
    return muts


@pytest.fixture()
def wire_debug():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    wiredbg.set_wire_debug(True)
    yield
    wiredbg.set_wire_debug(False)
    GLOBAL_REGISTRY.enabled = prev


@pytest.fixture()
def metrics_on():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    yield
    GLOBAL_REGISTRY.enabled = prev


@pytest.fixture()
def ledger():
    """resourceDebug analog: track transport resources during the fuzz
    and require a clean ledger after teardown."""
    led = get_resource_ledger()
    was = led.enabled
    led.enabled = True
    yield led
    led.enabled = was


# -- decode_msg fuzz (pure codec layer) ---------------------------------------


def test_decode_fuzz_fail_clean():
    """Every hostile frame either decodes or raises WireFormatError (a
    ValueError) — never any other exception, never a hang."""
    muts = _mutants(random.Random(0xC0DEC))
    assert len(muts) >= 200, len(muts)
    outcomes = {"ok": 0, "rejected": 0}
    for m in muts:
        try:
            decode_msg(m)
            outcomes["ok"] += 1
        except WireFormatError as e:
            assert isinstance(e, ValueError)
            outcomes["rejected"] += 1
    assert outcomes["rejected"] > 100, outcomes
    # the decoder holds no state: valid frames still decode after
    for f in _corpus():
        assert decode_msg(f).encode() == f


def test_decode_rejections_carry_structure():
    truncated = _corpus()[0][:6]
    with pytest.raises(WireFormatError):
        decode_msg(truncated)
    unknown = struct.pack("<ii", 12, 99) + b"\x00" * 4
    with pytest.raises(WireFormatError) as ei:
        decode_msg(unknown)
    assert ei.value.unknown_type and ei.value.msg_type == 99


# -- live engines: raw-socket frame injection ---------------------------------


def _handshake(port, version=wire.WIRE_VERSION, src_port=55555):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(10)
    s.sendall(wire._HELLO.pack(
        wire._MAGIC,
        wire._TYPE_BY_INDEX.index(ChannelType.RPC_REQUESTOR),
        src_port, version,
    ))
    ack = s.recv(1)
    return s, ack


def _recv_eof(s, timeout=10):
    s.settimeout(timeout)
    try:
        return s.recv(1) == b""
    except OSError:
        return True  # reset counts as closed


def _rpc_frame(payload):
    return wire._HDR.pack(wire.OP_RPC, len(payload)) + payload


def _fuzz_one_engine(port, async_mode, wiredbg_engine):
    """Shared engine harness: malformed RPC frames are dropped one by
    one (channel survives), an unknown opcode kills only that channel,
    and the node keeps accepting/dispatching afterwards."""
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportAsyncDispatcher": async_mode,
    })
    net = TcpNetwork()
    node = Node(("127.0.0.1", port), conf)
    net.register(node)
    sentinel = CleanShuffleMsg(424242).encode()
    seen = threading.Event()

    def on_frame(_channel, frame):
        if bytes(frame) == sentinel:
            seen.set()

    node.set_receive_listener(on_frame)

    def rejected():
        return counter(
            "wire_frames_rejected_total",
            engine=wiredbg_engine, opcode="rpc",
        ).value

    try:
        base_rej = rejected()
        s, ack = _handshake(port)
        assert ack == b"\x01"
        muts = _mutants(random.Random(0xBADF00D + port))
        for m in muts:
            s.sendall(_rpc_frame(m))
        # the channel survived every malformed frame: a valid frame
        # still reaches the application listener on the SAME socket
        s.sendall(_rpc_frame(sentinel))
        assert seen.wait(20), "valid frame lost after fuzz"
        assert rejected() - base_rej > 100
        # unknown opcode = desynced stream: THIS channel dies...
        s.sendall(wire._HDR.pack(77, 0))
        assert _recv_eof(s), "channel with desynced framing not closed"
        # ...but the node is healthy: fresh connection, frame dispatched
        seen.clear()
        s2, ack2 = _handshake(port, src_port=55556)
        assert ack2 == b"\x01"
        s2.sendall(_rpc_frame(sentinel))
        assert seen.wait(20), "node unhealthy after channel death"
        s2.close()
        s.close()
    finally:
        node.stop()
        net.unregister(node)


def test_threaded_engine_survives_frame_fuzz(wire_debug, ledger):
    _fuzz_one_engine(BASE_PORT, "off", "tcp")
    assert ledger.outstanding() == {}, ledger.outstanding()


def test_async_engine_survives_frame_fuzz(wire_debug, ledger):
    _fuzz_one_engine(BASE_PORT + 20, "on", "dispatcher")
    assert ledger.outstanding() == {}, ledger.outstanding()


# -- lying read-response bodies vs both requester state machines --------------


def _lying_responder(port, ready, n_lie):
    """Fake peer: acks the hello, reads the OP_READ_REQ frame, then
    answers with a block-length prefix that exceeds the response body."""
    srv = socket.create_server(("127.0.0.1", port))
    ready.set()
    sock, _addr = srv.accept()
    sock.settimeout(10)
    try:
        hello = b""
        while len(hello) < wire._HELLO.size:
            hello += sock.recv(wire._HELLO.size - len(hello))
        sock.sendall(b"\x01")
        hdr = b""
        while len(hdr) < wire._HDR.size:
            hdr += sock.recv(wire._HDR.size - len(hdr))
        opcode, length = wire._HDR.unpack(hdr)
        assert opcode == wire.OP_READ_REQ
        payload = b""
        while len(payload) < length:
            payload += sock.recv(length - len(payload))
        req_id, _count = wire._REQ_HDR.unpack_from(payload, 0)
        body = (
            wire._RESP_HDR.pack(req_id, 0)
            + wire._LEN.pack(n_lie)
            + b"xx"  # far fewer bytes than the prefix claims
        )
        sock.sendall(wire._HDR.pack(wire.OP_READ_RESP, len(body)) + body)
        _recv_eof(sock)  # hold the socket until the requester gives up
    finally:
        sock.close()
        srv.close()


@pytest.mark.parametrize("async_mode,port", [
    ("off", BASE_PORT + 40),
    ("on", BASE_PORT + 60),
])
def test_lying_block_length_prefix_fails_structured(
    async_mode, port, wire_debug, ledger
):
    """A response whose block-length prefix exceeds the frame's actual
    body must fail the read with a TransportError on both engines —
    never allocate from the lie, never hang waiting for phantom
    bytes."""
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportAsyncDispatcher": async_mode,
        "spark.shuffle.tpu.connectTimeout": "5s",
    })
    net = TcpNetwork()
    node = Node(("127.0.0.1", port), conf)
    net.register(node)
    ready = threading.Event()
    peer_port = port + 7
    t = threading.Thread(
        target=_lying_responder, args=(peer_port, ready, 1 << 29),
        daemon=True,
    )
    t.start()
    assert ready.wait(5)
    done = threading.Event()
    res = {}
    try:
        ch = node.get_channel(
            ("127.0.0.1", peer_port),
            ChannelType.READ_REQUESTOR, net.connect,
        )
        ch.read_blocks(
            [BlockLocation(0, 100, 1)],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("err", e), done.set()),
            ),
        )
        assert done.wait(20), "lying response hung the requester"
        assert "err" in res, res
        assert isinstance(res["err"], TransportError)
    finally:
        node.stop()
        net.unregister(node)
        t.join(timeout=10)
    assert ledger.outstanding() == {}, ledger.outstanding()


# -- loopback plane: dropped frames must still return recv credits ------------


def test_loopback_drops_bad_frames_and_credits_flow(wire_debug):
    """With wireDebug on, the loopback dispatch plane drops malformed
    frames (counted) while their recv slots are still consumed — far
    more bad frames than any credit window must all complete, and a
    trailing valid frame still arrives."""
    net = LoopbackNetwork()
    a = Node(("127.0.0.1", BASE_PORT + 80), TpuShuffleConf())
    b = Node(("127.0.0.1", BASE_PORT + 87), TpuShuffleConf())
    net.register(a)
    net.register(b)
    sentinel = CleanShuffleMsg(99).encode()
    seen = threading.Event()
    got = []

    def on_frame(_channel, frame):
        got.append(bytes(frame))
        if bytes(frame) == sentinel:
            seen.set()

    b.set_receive_listener(on_frame)

    def rejected():
        return counter(
            "wire_frames_rejected_total", engine="loopback", opcode="rpc"
        ).value

    base = rejected()
    try:
        ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, net.connect)
        bad = b"\xde\xad\xbe\xef"
        sent = threading.Event()
        for i in range(128):
            ch.send_rpc([bad], FnCompletionListener())
        ch.send_rpc([sentinel], FnCompletionListener(
            lambda *_a: sent.set(), lambda _e: sent.set()
        ))
        assert sent.wait(20), "sends stalled: dropped frames leaked credits"
        assert seen.wait(20), "valid frame lost behind dropped frames"
        assert rejected() - base >= 128
        assert bad not in got, "malformed frame reached the listener"
    finally:
        a.stop()
        b.stop()
        net.unregister(a)
        net.unregister(b)


# -- control plane: unknown msg_type is counted, not a crash ------------------


def test_manager_counts_and_drops_unknown_control_frames(metrics_on):
    """satellite 1: a control frame with an unknown MSG_TYPE (or a
    malformed body) must be counted + dropped by the manager's receive
    dispatch — a structured one-frame loss, never an exception up the
    transport stack."""
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": BASE_PORT + 100,
    })
    driver = TpuShuffleManager(
        conf, is_driver=True, network=LoopbackNetwork(),
        port=BASE_PORT + 100, stage_to_device=False,
    )
    try:
        def unknown_count(kind):
            return counter(
                "wire_unknown_frames_total", engine="control", kind=kind
            ).value

        base_t, base_m = unknown_count("msg_type"), unknown_count("malformed")
        driver._receive(None, struct.pack("<ii", 12, 99) + b"\x00" * 4)
        driver._receive(None, b"\x03")  # truncated header
        hello = HelloMsg(_smid(1), channel_port=1).encode()
        driver._receive(None, hello[:-2])  # schema underrun
        assert unknown_count("msg_type") - base_t == 1
        assert unknown_count("malformed") - base_m == 2
    finally:
        driver.stop()


# -- hello/version handshake (satellite 2) ------------------------------------


@pytest.mark.parametrize("async_mode,port", [
    ("off", BASE_PORT + 120),
    ("on", BASE_PORT + 140),
])
def test_old_version_hello_rejected_structurally(async_mode, port, metrics_on):
    """A pre-upgrade hello (version 0 — what pre-versioning peers sent
    in the old pad slot) gets the structured NAK naming both versions,
    on both engines' accept paths."""
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.transportAsyncDispatcher": async_mode,
    })
    net = TcpNetwork()
    node = Node(("127.0.0.1", port), conf)
    net.register(node)
    try:
        base = counter("wire_version_rejects_total").value
        s, ack = _handshake(port, version=0)
        assert ack == b"\x00"
        srv_ver, hello_ver = wire._HELLO_REJ.unpack(
            s.recv(wire._HELLO_REJ.size)
        )
        assert (srv_ver, hello_ver) == (wire.WIRE_VERSION, 0)
        assert _recv_eof(s)
        assert counter("wire_version_rejects_total").value - base == 1
        # the node still accepts current-version hellos
        s2, ack2 = _handshake(port)
        assert ack2 == b"\x01"
        s2.close()
    finally:
        node.stop()
        net.unregister(node)


def test_connector_names_both_versions_on_rejection():
    """The connecting side of a version NAK raises a TransportError
    naming the peer's required version AND the hello's own."""
    port = BASE_PORT + 160
    ready = threading.Event()

    def future_server():
        srv = socket.create_server(("127.0.0.1", port))
        ready.set()
        sock, _addr = srv.accept()
        hello = b""
        while len(hello) < wire._HELLO.size:
            hello += sock.recv(wire._HELLO.size - len(hello))
        sock.sendall(b"\x00" + wire._HELLO_REJ.pack(9, wire.WIRE_VERSION))
        sock.close()
        srv.close()

    t = threading.Thread(target=future_server, daemon=True)
    t.start()
    assert ready.wait(5)
    net = TcpNetwork()
    node = Node(("127.0.0.1", port + 7), TpuShuffleConf({
        "spark.shuffle.tpu.connectTimeout": "5s",
    }))
    try:
        with pytest.raises(TransportError) as ei:
            net.connect(
                node, ("127.0.0.1", port), ChannelType.RPC_REQUESTOR
            )
        msg = str(ei.value)
        assert "wire version 9" in msg
        assert f"spoke {wire.WIRE_VERSION}" in msg
    finally:
        node.stop()
        t.join(timeout=10)
