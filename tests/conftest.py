"""Test harness: run everything on an 8-way virtual CPU device mesh.

Must set the env vars BEFORE jax is imported anywhere (SURVEY.md §4:
device-count spoofing via --xla_force_host_platform_device_count).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override any preset TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize registers the real-TPU ("axon") backend
# via jax.config, which overrides JAX_PLATFORMS from the env — force the
# spoofed-CPU mesh back on for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected ≥8 spoofed CPU devices, got {len(devs)}"
    return devs
