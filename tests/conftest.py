"""Test harness: run everything on an 8-way virtual CPU device mesh.

Must set the env vars BEFORE jax is imported anywhere (SURVEY.md §4:
device-count spoofing via --xla_force_host_platform_device_count).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override any preset TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize registers the real-TPU ("axon") backend
# via jax.config, which overrides JAX_PLATFORMS from the env — force the
# spoofed-CPU mesh back on for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected ≥8 spoofed CPU devices, got {len(devs)}"
    return devs


# ProcessCluster fixture ports: below the kernel ephemeral floor
# (32768) so a transient client socket can never squat a base, spaced
# wider than any per-fleet spread (driver + 8 executors × 40)
_CLUSTER_PORT = [24200]


def _next_cluster_port() -> int:
    p = _CLUSTER_PORT[0]
    _CLUSTER_PORT[0] += 500
    return p


@pytest.fixture
def cluster(tmp_path):
    """A REAL 2-process cluster: driver in this process + two full
    TpuShuffleManager executor processes over TCP sockets.  Tests drive
    it through the pipe command protocol (register/write/read); obs
    dumps (flight recorder + logs) land in the workdir and are merged
    at teardown."""
    from sparkrdma_tpu.transport.simfleet import ProcessCluster

    c = ProcessCluster(
        2, _next_cluster_port(),
        conf={
            "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
            "spark.shuffle.tpu.connectTimeout": "10s",
            "spark.shuffle.tpu.fetchRetryWaitMs": "100ms",
        },
        workdir=str(tmp_path / "cluster"),
    )
    yield c
    c.stop()
    c.collect()


@pytest.fixture(scope="session", autouse=True)
def collect_flight_recorder_dump():
    """Fleet-wide observability collection: with
    ``SPARKRDMA_TPU_OBS_DUMP_DIR`` set, this process retains the
    flight recorder for the whole session and leaves one dump at exit;
    merge the per-process files with
    ``python tools/trace_report.py <dir>/*.json`` for one
    cross-process trace of the run.  Opt-in only — holding the
    recorder open changes the (normally off-by-default) enabled flag
    some lifecycle assertions check, so this is a debugging mode, not
    part of the default gate."""
    dump_dir = os.environ.get("SPARKRDMA_TPU_OBS_DUMP_DIR")
    if not dump_dir:
        yield
        return
    from sparkrdma_tpu.obs import RECORDER
    from sparkrdma_tpu.obs.collect import write_dump

    RECORDER.retain(ring_size=1 << 16)
    yield
    write_dump(
        os.path.join(dump_dir, f"flightrec-session-{os.getpid()}.json"),
        reason="session_end",
    )
    RECORDER.release()
