"""Bounded connection fabric (ROADMAP item 1): the LRU channel cache
(eviction + transparent reconnect, both engines), the borrowable lane
pool, read-group invalidation, responder-side cleanup on peer-initiated
close, and the teardown-interruptible connect backoff."""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.arena import ArenaManager
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.transport.channel import ChannelType, FnCompletionListener
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.transport.simfleet import SimPeerFleet
from sparkrdma_tpu.utils.types import BlockLocation

BASE_PORT = 26100

_PATTERN = (np.arange(4 << 20, dtype=np.uint32) % 251).astype(np.uint8)


@pytest.fixture
def registry_on():
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.enabled = True
    yield GLOBAL_REGISTRY
    GLOBAL_REGISTRY.enabled = prev


def _conf(extra=None):
    d = {
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
    }
    d.update(extra or {})
    return TpuShuffleConf(d)


def _group_read(group, locs, timeout=30):
    done = threading.Event()
    res = {}
    group.read_blocks(
        locs,
        FnCompletionListener(
            lambda blocks: (res.setdefault("blocks", blocks), done.set()),
            lambda e: (res.setdefault("error", e), done.set()),
        ),
    )
    assert done.wait(timeout), "group read hung"
    if "error" in res:
        raise res["error"]
    return res["blocks"]


def _as_np(blk):
    if isinstance(blk, np.ndarray):
        return blk
    return np.frombuffer(memoryview(blk), np.uint8)


def _check_block(blk, loc):
    got = _as_np(blk)
    assert got.shape[0] == loc.length
    assert np.array_equal(
        got, _PATTERN[loc.address:loc.address + loc.length]
    ), f"corrupt block {loc}"


@pytest.mark.parametrize("async_disp,fleet_port,node_port", [
    ("off", BASE_PORT, BASE_PORT + 90),
    ("on", BASE_PORT + 100, BASE_PORT + 190),
])
def test_striped_reads_bit_exact_across_forced_evictions(
        registry_on, async_disp, fleet_port, node_port):
    """A cache cap far below one peer's own lane count forces
    evictions MID-WORKLOAD on every read cycle; striped payloads must
    stay bit-exact through evict → reconnect on both engines, and the
    eviction/reconnect counters must prove the churn actually
    happened."""
    fleet = SimPeerFleet(3, fleet_port, _PATTERN)
    conf = _conf({
        # 3 peers × (1 small + 2 data lanes) = 9 wanted, cap 2
        "spark.shuffle.tpu.transportMaxCachedChannels": 2,
        "spark.shuffle.tpu.transportAsyncDispatcher": async_disp,
    })
    node = Node(("127.0.0.1", node_port), conf)
    if async_disp == "on":
        node.get_dispatcher()
    try:
        ev0 = GLOBAL_REGISTRY.counter(
            "transport_channel_evictions_total").value
        locs = [
            BlockLocation(11, 900_000, 1),   # striped
            BlockLocation(3, 1000, 1),       # small lane
        ]
        for cycle in range(6):
            for peer in fleet.addresses:
                group = node.get_read_group(peer, TcpNetwork().connect)
                blocks = _group_read(group, locs)
                for loc, blk in zip(locs, blocks):
                    _check_block(blk, loc)
        with node._active_lock:
            cached = len(node._active)
        assert cached <= 2, f"cache over cap: {cached}"
        assert GLOBAL_REGISTRY.counter(
            "transport_channel_evictions_total").value > ev0
        assert GLOBAL_REGISTRY.counter(
            "transport_channel_reconnects_total").value > 0
    finally:
        node.stop()
        fleet.close()


def test_eviction_refuses_channels_with_in_flight_ops(registry_on):
    """A channel with outstanding ops is never evicted: the cache
    tolerates transient over-cap occupancy instead (refusal counter),
    and shrinks once the op completes."""
    conf = _conf({
        "spark.shuffle.tpu.transportMaxCachedChannels": 1,
        "spark.shuffle.tpu.transportServeThreads": 1,
    })
    net = LoopbackNetwork()
    a = Node(("127.0.0.1", BASE_PORT + 300), conf)
    b = Node(("127.0.0.1", BASE_PORT + 301), conf)
    c = Node(("127.0.0.1", BASE_PORT + 302), conf)
    for n in (a, b, c):
        net.register(n)
    arena = ArenaManager()
    seg = arena.register(_PATTERN, zero_copy_ok=True)
    b.register_block_store(seg.mkey, arena)
    gate = threading.Event()
    # wedge b's only serve worker so a's read to b stays in flight
    b.submit_serve(gate.wait, (30,), cost=0)
    try:
        ch_b = a.get_channel(b.address, ChannelType.READ_REQUESTOR,
                             net.connect)
        done = threading.Event()
        res = {}
        ch_b.read_blocks(
            [BlockLocation(0, 4096, seg.mkey)],
            FnCompletionListener(
                lambda blocks: (res.setdefault("ok", blocks), done.set()),
                lambda e: (res.setdefault("error", e), done.set()),
            ),
        )
        assert ch_b.in_flight() > 0
        refusals0 = GLOBAL_REGISTRY.counter(
            "transport_channel_evict_refusals_total").value
        # inserting a second channel breaches cap=1; the only eviction
        # candidate is busy → refused, both stay connected
        ch_c = a.get_channel(c.address, ChannelType.RPC_REQUESTOR,
                             net.connect)
        assert GLOBAL_REGISTRY.counter(
            "transport_channel_evict_refusals_total").value > refusals0
        assert ch_b.is_connected() and ch_c.is_connected()
        with a._active_lock:
            assert len(a._active) == 2  # tolerated overflow
        gate.set()
        assert done.wait(10), "gated read never completed"
        assert "ok" in res, res.get("error")
        _check_block(res["ok"][0], BlockLocation(0, 4096, seg.mkey))
        # with the op settled the cache can shrink back under cap
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            a._maybe_evict()
            with a._active_lock:
                if len(a._active) <= 1:
                    break
            time.sleep(0.02)
        with a._active_lock:
            assert len(a._active) <= 1
    finally:
        gate.set()
        for n in (a, b, c):
            n.stop()
            net.unregister(n)


def test_chaos_tiny_cap_concurrent_multi_peer_fetch(registry_on):
    """LRU cap of 3 under concurrent multi-peer striped fetch: every
    read must complete bit-exact — eviction never tears a channel out
    from under a posted op, and a post racing an eviction re-resolves
    through the cache."""
    n_peers = 6
    fleet = SimPeerFleet(n_peers, BASE_PORT + 400, _PATTERN)
    conf = _conf({
        "spark.shuffle.tpu.transportMaxCachedChannels": 3,
        "spark.shuffle.tpu.transportLanePoolSize": 4,
    })
    node = Node(("127.0.0.1", BASE_PORT + 490), conf)
    connect = TcpNetwork().connect
    errors = []
    try:
        def worker(seed):
            rng = np.random.default_rng(seed)
            for i in range(8):
                peer = fleet.addresses[int(rng.integers(n_peers))]
                size = int(rng.integers(200, 600_000))
                addr = int(rng.integers(0, len(_PATTERN) - size))
                loc = BlockLocation(addr, size, 1)
                try:
                    group = node.get_read_group(peer, connect)
                    blocks = _group_read(group, [loc], timeout=60)
                    _check_block(blocks[0], loc)
                except Exception as e:  # noqa: BLE001 - chaos harness
                    errors.append((seed, i, e))

        threads = [
            threading.Thread(target=worker, args=(s,), daemon=True)
            for s in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "chaos worker hung"
        assert not errors, errors
        assert GLOBAL_REGISTRY.counter(
            "transport_channel_evictions_total").value > 0
    finally:
        node.stop()
        fleet.close()


def test_lane_pool_bounds_borrowed_width_and_falls_back(registry_on):
    """A 1-token lane pool narrows striping to one data lane; a
    0-available pool demotes the read to the small lane — both stay
    bit-exact, and tokens return on completion."""
    fleet = SimPeerFleet(1, BASE_PORT + 500, _PATTERN)
    conf = _conf({"spark.shuffle.tpu.transportLanePoolSize": 1})
    node = Node(("127.0.0.1", BASE_PORT + 590), conf)
    try:
        loc = BlockLocation(7, 1 << 20, 1)
        group = node.get_read_group(fleet.addresses[0], TcpNetwork().connect)
        _check_block(_group_read(group, [loc])[0], loc)
        assert node.lane_pool._free == 1, "lane token not returned"
        # drain the pool: the next read falls back to the small lane
        assert node.lane_pool.try_borrow(1) == 1
        ex0 = GLOBAL_REGISTRY.counter(
            "transport_lane_pool_exhausted_total").value
        _check_block(_group_read(group, [loc])[0], loc)
        assert GLOBAL_REGISTRY.counter(
            "transport_lane_pool_exhausted_total").value > ex0
        node.lane_pool.release(1)
    finally:
        node.stop()
        fleet.close()


def test_read_group_invalidated_when_peer_unreachable(registry_on):
    """A dead peer must not pin its read group (and gauge) for the
    node's lifetime: the connect-exhausted path invalidates it."""
    net = LoopbackNetwork()
    conf = _conf({"spark.shuffle.tpu.maxConnectionAttempts": 2})
    a = Node(("127.0.0.1", BASE_PORT + 600), conf)
    b = Node(("127.0.0.1", BASE_PORT + 601), conf)
    net.register(a)
    net.register(b)
    try:
        group = a.get_read_group(b.address, net.connect)
        assert b.address in a._read_groups
        b.stop()
        net.unregister(b)
        with pytest.raises(Exception):
            _group_read(group, [BlockLocation(0, 4096, 1)], timeout=30)
        # the group read fails via listeners; a follow-up channel
        # resolve exhausts its connect attempts and invalidates
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                _group_read(group, [BlockLocation(0, 4096, 1)], timeout=30)
            except Exception:
                pass
            if b.address not in a._read_groups:
                break
        assert b.address not in a._read_groups
    finally:
        a.stop()
        net.unregister(a)
        net.unregister(b)


def test_read_group_invalidated_when_lanes_evicted(registry_on):
    """Evicting a peer's LAST cached channel drops its read group —
    an idle peer costs zero connections AND zero group state."""
    fleet = SimPeerFleet(4, BASE_PORT + 700, _PATTERN)
    conf = _conf({"spark.shuffle.tpu.transportMaxCachedChannels": 2})
    node = Node(("127.0.0.1", BASE_PORT + 790), conf)
    connect = TcpNetwork().connect
    try:
        first = fleet.addresses[0]
        loc = BlockLocation(0, 300_000, 1)
        _check_block(
            _group_read(node.get_read_group(first, connect), [loc])[0], loc
        )
        assert first in node._read_groups
        for peer in fleet.addresses[1:]:
            _check_block(
                _group_read(node.get_read_group(peer, connect), [loc])[0],
                loc,
            )
        # all of peer 0's channels were evicted by the later fetches
        with node._active_lock:
            assert not any(k[0] == first for k in node._active)
        assert first not in node._read_groups
        # ...and the next fetch simply rebuilds group + channels
        _check_block(
            _group_read(node.get_read_group(first, connect), [loc])[0], loc
        )
    finally:
        node.stop()
        fleet.close()


def test_responder_prunes_passive_channel_and_fd_on_peer_close():
    """Threaded engine, responder side: a requester closing (evicting)
    its end must not leak the responder's accepted socket fd or its
    passive-list entry until node teardown — the reader loop closes
    the fd and prunes the caches on its way out."""
    import os

    conf = _conf({"spark.shuffle.tpu.transportAsyncDispatcher": "off"})
    net = TcpNetwork()
    a = Node(("127.0.0.1", BASE_PORT + 800), conf)
    b = Node(("127.0.0.1", BASE_PORT + 807), conf)
    net.register(a)
    net.register(b)
    arena = ArenaManager()
    seg = arena.register(_PATTERN, zero_copy_ok=True)
    b.register_block_store(seg.mkey, arena)
    try:
        fds0 = len(os.listdir("/proc/self/fd"))
        ch = a.get_channel(b.address, ChannelType.READ_REQUESTOR,
                           net.connect)
        done = threading.Event()
        ch.read_blocks(
            [BlockLocation(0, 4096, seg.mkey)],
            FnCompletionListener(lambda blocks: done.set(),
                                 lambda e: done.set()),
        )
        assert done.wait(10)
        with b._passive_lock:
            assert len(b._passive) == 1
        ch.stop()  # the requester-side eviction analog
        with a._active_lock:
            a._active.clear()
            a._last_use.clear()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with b._passive_lock:
                if not b._passive:
                    break
            time.sleep(0.02)
        with b._passive_lock:
            assert not b._passive, "responder kept dead passive channel"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(os.listdir("/proc/self/fd")) <= fds0:
                break
            time.sleep(0.02)
        assert len(os.listdir("/proc/self/fd")) <= fds0, (
            "responder leaked the accepted socket's fd"
        )
    finally:
        a.stop()
        b.stop()
        net.unregister(a)
        net.unregister(b)


def test_stop_interrupts_connect_backoff():
    """Node teardown mid-retry must interrupt the connect backoff wait
    immediately instead of sleeping it out (satellite: _stopped.wait,
    not time.sleep)."""
    conf = _conf({"spark.shuffle.tpu.maxConnectionAttempts": 100,
                  "spark.shuffle.tpu.connectTimeout": "1s"})
    node = Node(("127.0.0.1", BASE_PORT + 900), conf)
    net = TcpNetwork()
    finished = threading.Event()

    def connect_forever():
        try:
            # nothing listens at the peer port: every attempt fails
            # fast and enters the (growing) backoff wait
            node.get_channel(("127.0.0.1", BASE_PORT + 901),
                             ChannelType.READ_REQUESTOR, net.connect)
        except Exception:
            pass
        finished.set()

    t = threading.Thread(target=connect_forever, daemon=True)
    t.start()
    time.sleep(0.6)  # deep enough that the backoff is at ~0.5s waits
    assert not finished.is_set(), "connect loop ended before stop"
    t0 = time.monotonic()
    node.stop()
    assert finished.wait(1.0), "stop did not interrupt the backoff"
    assert time.monotonic() - t0 < 1.0
