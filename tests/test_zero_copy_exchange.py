"""Zero-copy pipelined bulk-exchange data path (ISSUE 2):

- ``TileExchange.exchange_into``: preallocated contiguous source rows
  in, destination-row VIEWS out — bit-exact with ``exchange_bytes``.
- ``BulkShuffleSession`` accepting array rows (and downgrading mixed
  legacy/array rounds).
- The double-buffered windowed pipeline: bit-exact vs the serial loop,
  prompt failure of in-flight AND being-assembled windows on abort.
- The tier-1 perf smoke: assembly materializes no per-block ``bytes``
  (copy counter stays zero) while the zero-copy counters move.
"""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.parallel.exchange import (
    DestRowView,
    TileExchange,
    row_offsets,
)
from sparkrdma_tpu.parallel.mesh import make_mesh
from sparkrdma_tpu.shuffle.bulk import BulkShuffleSession
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork


def test_row_offsets():
    offs = row_offsets([3, 0, 5, 2])
    assert offs.tolist() == [0, 3, 3, 8, 10]
    assert row_offsets([]).tolist() == [0]


def test_dest_row_view_slices():
    buf = np.arange(10, dtype=np.uint8)
    v = DestRowView(buf, row_offsets([4, 0, 6]))
    assert len(v) == 3
    assert v[0].tolist() == [0, 1, 2, 3]
    assert v[1].tolist() == []
    assert v[2].tolist() == [4, 5, 6, 7, 8, 9]
    assert v.nbytes == 10
    # zero-copy: slices share the row buffer
    assert v[2].base is buf or v[2].base is v[2].base


def _random_lengths(rng, D, max_len=4000):
    return rng.integers(0, max_len, size=(D, D)).astype(np.int64)


def _rows_from_streams(streams, lengths):
    """Pack per-pair byte streams into contiguous per-source rows."""
    rows = {}
    for s in range(len(streams)):
        offs = row_offsets(lengths[s])
        row = np.empty(int(offs[-1]), np.uint8)
        for d in range(len(streams)):
            if lengths[s][d]:
                row[int(offs[d]):int(offs[d + 1])] = np.frombuffer(
                    streams[s][d], np.uint8
                )
        rows[s] = row
    return rows


def test_exchange_into_matches_exchange_bytes(devices):
    mesh = make_mesh(8)
    ex = TileExchange(mesh, tile_bytes=1 << 10)
    D = ex.n_devices
    rng = np.random.default_rng(7)
    lengths = _random_lengths(rng, D)
    streams = [
        [rng.bytes(int(lengths[s, d])) for d in range(D)]
        for s in range(D)
    ]
    legacy = ex.exchange_bytes(streams, lengths=lengths)
    rows = _rows_from_streams(streams, lengths)
    result = ex.exchange_into(lengths, rows)
    for d in range(D):
        view = result[d]
        assert isinstance(view, DestRowView)
        for s in range(D):
            got = view[s]
            assert bytes(memoryview(got)) == legacy[d][s], (s, d)
            assert bytes(memoryview(got)) == streams[s][d], (s, d)


def test_exchange_into_multi_round(devices):
    """Small tiles force many rounds through the in-flight window; the
    round/offset bookkeeping must reassemble every stream exactly."""
    mesh = make_mesh(8)
    ex = TileExchange(mesh, tile_bytes=256, max_rounds_in_flight=3)
    D = ex.n_devices
    rng = np.random.default_rng(8)
    lengths = _random_lengths(rng, D, max_len=5000)
    streams = [
        [rng.bytes(int(lengths[s, d])) for d in range(D)]
        for s in range(D)
    ]
    result = ex.exchange_into(
        lengths, _rows_from_streams(streams, lengths)
    )
    for d in range(D):
        for s in range(D):
            assert bytes(memoryview(result[d][s])) == streams[s][d]
    assert ex.rounds_executed > 3


def test_exchange_into_empty(devices):
    ex = TileExchange(make_mesh(4))
    lengths = np.zeros((4, 4), np.int64)
    result = ex.exchange_into(
        lengths, {s: np.empty(0, np.uint8) for s in range(4)}
    )
    for d in range(4):
        for s in range(4):
            assert len(result[d][s]) == 0


def test_exchange_into_validates_rows(devices):
    ex = TileExchange(make_mesh(4), tile_bytes=1 << 10)
    lengths = np.full((4, 4), 10, np.int64)
    rows = {s: np.zeros(40, np.uint8) for s in range(4)}
    with pytest.raises(ValueError, match="vouched source"):
        ex.exchange_into(lengths, {s: rows[s] for s in range(3)},
                         local_sources=frozenset(range(4)))
    rows[2] = np.zeros(39, np.uint8)  # one byte short
    with pytest.raises(ValueError, match="source row 2"):
        ex.exchange_into(lengths, rows)


def test_exchange_into_integrity_and_out_alloc(devices):
    ex = TileExchange(make_mesh(4), tile_bytes=512,
                      verify_integrity=True)
    rng = np.random.default_rng(9)
    lengths = _random_lengths(rng, 4, max_len=2000)
    streams = [
        [rng.bytes(int(lengths[s, d])) for d in range(4)]
        for s in range(4)
    ]
    allocs = []

    def alloc(n):
        buf = np.empty(n, np.uint8)
        allocs.append(n)
        return buf

    result = ex.exchange_into(
        lengths, _rows_from_streams(streams, lengths), out_alloc=alloc
    )
    assert ex.stats()["integrity_failures"] == 0
    # destination rows really came from the caller's allocator, sized
    # at each destination's exact column sum
    expect = sorted(
        int(lengths[:, d].sum()) for d in range(4)
        if int(lengths[:, d].sum())
    )
    assert sorted(allocs) == expect
    for d in range(4):
        for s in range(4):
            assert bytes(memoryview(result[d][s])) == streams[s][d]


def test_session_array_and_mixed_rows(devices):
    """Array rows ride exchange_into; a mixed round (one legacy list
    contributor) downgrades to the bytes path with identical output."""
    E = 2
    rng = np.random.default_rng(11)
    lengths = np.array([[100, 200], [300, 50]], np.int64)
    streams = [
        [rng.bytes(int(lengths[s, d])) for d in range(E)]
        for s in range(E)
    ]
    rows = _rows_from_streams(streams, lengths)

    for mixed in (False, True):
        session = BulkShuffleSession(
            TileExchange(make_mesh(E), tile_bytes=1 << 12), E
        )
        out = {}

        def run(me, contribution):
            out[me] = session.run(me, contribution, lengths)

        contrib1 = list(streams[1]) if mixed else rows[1]
        t = threading.Thread(
            target=run, args=(1, contrib1), daemon=True
        )
        t.start()
        time.sleep(0.05)
        run(0, rows[0])
        t.join(timeout=30)
        for me in range(E):
            row = out[me][me]
            for s in range(E):
                assert bytes(memoryview(row[s])) == streams[s][me], (
                    mixed, me, s,
                )


# -- windowed plane: pipelined vs serial -------------------------------------

def _cluster(base_port, conf_extra=None, n_exec=2):
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane

    net = LoopbackNetwork()
    overrides = {
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
        "spark.shuffle.tpu.readPlane": "windowed",
    }
    overrides.update(conf_extra or {})
    conf = TpuShuffleConf(overrides)
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(n_exec)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n_exec for e in executors):
            break
        time.sleep(0.01)
    session = BulkShuffleSession(
        TileExchange(make_mesh(n_exec), tile_bytes=1 << 12), n_exec,
        timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
    )
    for e in executors:
        e.windowed_plane = WindowedReadPlane(e, session=session)
    return net, conf, driver, executors, session


def _write_maps(driver, executors, sid, num_maps, num_parts, seed=0):
    rng = np.random.default_rng(seed)
    part = HashPartitioner(num_parts)
    handle = driver.register_shuffle(sid, num_maps, part)
    records_per_map = [
        [(f"m{m}k{j}", rng.bytes(int(rng.integers(1, 200))))
         for j in range(30)]
        for m in range(num_maps)
    ]
    for m, recs in enumerate(records_per_map):
        w = executors[m % len(executors)].get_writer(handle, m)
        w.write(recs)
        w.stop(True)
    return handle, part, records_per_map


def _read_all_blocks(executors, handle, num_parts):
    """Every partition's raw block payloads via reducer-issued reads;
    returns {pid: [bytes]} (payloads materialized for comparison)."""
    E = len(executors)
    out, errs = {}, {}

    def reduce_task(pid):
        try:
            r = executors[pid % E].get_reader(handle, pid, pid + 1, {})
            out[pid] = [
                bytes(memoryview(b)) if not isinstance(b, bytes)
                else b
                for b in r._iter_block_bytes()
            ]
        except BaseException as e:
            errs[pid] = e

    threads = [
        threading.Thread(target=reduce_task, args=(p,), daemon=True)
        for p in range(num_parts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    return out


def test_windowed_pipelined_bit_exact_vs_serial(devices):
    """The double-buffer sweep: identical shuffle data through the
    pipelined and serial window loops yields byte-identical block
    streams per partition."""
    blocks_by_mode = {}
    for base_port, pipelined in ((52200, True), (52400, False)):
        net, conf, driver, executors, _session = _cluster(
            base_port,
            {"spark.shuffle.tpu.bulkPipelineWindows": str(pipelined)},
        )
        try:
            handle, _part, _recs = _write_maps(
                driver, executors, 210, num_maps=6, num_parts=6,
                seed=42,
            )
            blocks_by_mode[pipelined] = _read_all_blocks(
                executors, handle, 6
            )
        finally:
            for m in executors + [driver]:
                m.stop()
    assert blocks_by_mode[True] == blocks_by_mode[False]
    assert any(v for v in blocks_by_mode[True].values())


def test_windowed_pipeline_abort_fails_all_windows_promptly(devices):
    """Poisoning the session mid-pipeline fails the in-flight window
    AND the being-assembled one: readers get FetchFailedError fast, no
    stage thread rides out the plan/barrier timeout."""
    from sparkrdma_tpu.shuffle.reader import FetchFailedError

    net, conf, driver, executors, session = _cluster(
        52600, {"spark.shuffle.tpu.bulkPipelineWindows": "true"}
    )
    try:
        E = len(executors)
        num_maps, num_parts = 6, 4
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(211, num_maps, part)
        for m in range(3):  # window 0 plannable; windows 1+ straggle
            w = executors[m % E].get_writer(handle, m)
            w.write([(f"m{m}k{j}", j) for j in range(20)])
            w.stop(True)
        results, errors = {}, {}

        def reduce_task(pid):
            try:
                r = executors[pid % E].get_reader(
                    handle, pid, pid + 1, {}
                )
                results[pid] = list(r.read())
            except BaseException as e:
                errors[pid] = e

        threads = [
            threading.Thread(target=reduce_task, args=(p,),
                             daemon=True)
            for p in range(num_parts)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(
                e.windowed_plane.window_events(211) for e in executors
            ):
                break
            time.sleep(0.01)
        assert all(
            e.windowed_plane.window_events(211) for e in executors
        ), "window 0 never exchanged"
        # the pipeline is now parked: window 1's plan barrier waits for
        # unpublished maps while its assembler sits in flight — poison
        t0 = time.monotonic()
        session.abort(RuntimeError("mid-pipeline participant loss"))
        for t in threads:
            t.join(timeout=20)
        took = time.monotonic() - t0
        assert not any(t.is_alive() for t in threads), "reader hung"
        assert not results, results
        assert set(errors) == set(range(num_parts))
        assert all(
            isinstance(e, FetchFailedError) for e in errors.values()
        ), errors
        assert took < 15, f"abort took {took:.1f}s"
    finally:
        for m in executors + [driver]:
            m.stop()


def test_windowed_zero_copy_smoke_counters(devices):
    """Tier-1 perf smoke (loopback, small payload): the assembly path
    materializes NO per-block bytes (counter absent/zero) while the
    zero-copy counters move, and at least one window staged while
    another exchanged."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    try:
        net, conf, driver, executors, _session = _cluster(
            52800, {
                "spark.shuffle.tpu.metrics": "true",
                "spark.shuffle.tpu.bulkPipelineWindows": "true",
            }
        )
        try:
            handle, part, recs = _write_maps(
                driver, executors, 212, num_maps=4, num_parts=4,
                seed=3,
            )
            got = _read_all_blocks(executors, handle, 4)
            assert any(got.values())
        finally:
            for m in executors + [driver]:
                m.stop()
        snap = GLOBAL_REGISTRY.snapshot()
        vals = {}
        for c in snap["counters"]:
            vals[c["name"]] = vals.get(c["name"], 0) + c["value"]
        assert vals.get("exchange_assembly_bytes_total", 0) > 0
        assert vals.get(
            "exchange_assembly_materialized_blocks_total", 0
        ) == 0, "assembly materialized per-block bytes"
        assert vals.get("exchange_copy_bytes_avoided_total", 0) > 0
        assert vals.get("exchange_windows_pipelined_total", 0) >= 1
    finally:
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()


# -- transport dispatcher CPU pinning (conf dispatcherCpuList) ---------------

def test_dispatcher_cpu_list_conf_parses():
    """The knob parses (range syntax, legacy alias, all-CPUs default)
    on every platform — pinning itself is covered below where
    sched_setaffinity exists."""
    conf = TpuShuffleConf({"spark.shuffle.rdma.cpuList": "0-1,3"})
    assert conf.parse_dispatcher_cpu_list(8) == [0, 1, 3]
    explicit = TpuShuffleConf(
        {"spark.shuffle.tpu.dispatcherCpuList": "2"}
    )
    assert explicit.parse_dispatcher_cpu_list(4) == [2]
    assert TpuShuffleConf().parse_dispatcher_cpu_list(4) == [0, 1, 2, 3]
    # deviceList remains a separate (mesh-device) namespace
    dev = TpuShuffleConf({"spark.shuffle.tpu.deviceList": "0"})
    assert dev.dispatcher_cpu_list == ""


@pytest.mark.skipif(
    not hasattr(__import__("os"), "sched_setaffinity"),
    reason="platform has no sched_setaffinity",
)
def test_dispatcher_threads_pinned_to_device_list():
    import os

    if (os.cpu_count() or 1) < 2:
        pytest.skip("needs >= 2 CPUs to observe a restricted mask")
    from sparkrdma_tpu.transport.node import Node

    # legacy reference spelling (spark.shuffle.rdma.cpuList) aliases
    # onto dispatcherCpuList — the RdmaThread comp-vector pinning
    # analog (deviceList stays a mesh-DEVICE selector)
    conf = TpuShuffleConf({"spark.shuffle.rdma.cpuList": "0"})
    assert conf.dispatcher_cpu_list == "0"
    assert conf.parse_dispatcher_cpu_list(os.cpu_count()) == [0]
    node = Node(("127.0.0.1", 0), conf)
    try:
        got = node.submit(
            lambda: sorted(os.sched_getaffinity(0))
        ).result(timeout=10)
        assert got == [0], got
    finally:
        node.stop()


def test_dispatcher_unpinned_without_device_list():
    import os

    from sparkrdma_tpu.transport.node import Node

    node = Node(("127.0.0.1", 0), TpuShuffleConf())
    try:
        assert node._cpu_pins is None
        if hasattr(os, "sched_getaffinity"):
            got = node.submit(
                lambda: sorted(os.sched_getaffinity(0))
            ).result(timeout=10)
            assert got == sorted(os.sched_getaffinity(0))
    finally:
        node.stop()
