"""Device-native exchange (ISSUE 20): the TPU/XLA collective plane as
the fast path.

- ``TileExchange.exchange_padded``: padded source rows in, device
  collective, padded destination views out — bit-exact with
  ``exchange_into`` in both the full-shot and windowed-rounds shapes.
- Cluster-level sweep: device-native vs host-staged vs socket reader
  over a forced 2-/4-device CPU mesh x pickle/columnar serializer x
  decodeThreads {0, 4} — identical records everywhere.
- ``deviceExchangeEnabled=off`` plan-identity pin: byte-identical block
  streams with the device path disabled.
- Collective/decode overlap: multi-round device exchanges emit early
  per-round block deliveries and stay bit-exact.
- Mid-round abort poisons the in-flight window promptly.
- ``DeviceStagingBridge`` framing and ``bucketize_segments`` offsets.
"""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.device_arena import DeviceStagingBridge
from sparkrdma_tpu.parallel.exchange import (
    PaddedDestRowView,
    PaddedSourceRow,
    TileExchange,
    row_offsets,
)
from sparkrdma_tpu.parallel.mesh import make_mesh
from sparkrdma_tpu.shuffle.bulk import BulkShuffleSession
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork

# distinct port band from the other cluster suites (they sit in the
# 40000-52xxx range); tier-1 runs suites sequentially so only lingering
# sockets matter
_NEXT_PORT = [53000]


def _ports():
    p = _NEXT_PORT[0]
    _NEXT_PORT[0] += 250
    return p


# -- exchange_padded: bit-exact vs exchange_into ------------------------------

def _random_plan(rng, D, max_len=4000):
    lengths = rng.integers(0, max_len, size=(D, D)).astype(np.int64)
    streams = [
        [rng.bytes(int(lengths[s, d])) for d in range(D)]
        for s in range(D)
    ]
    return lengths, streams


def _padded_rows(ex, lengths, streams):
    """Pack per-pair streams into the padded device framing."""
    D = ex.n_devices
    cols = ex.plan(lengths).total_cols
    rows = {}
    for s in range(D):
        buf = np.zeros(D * cols, np.uint8)
        for d in range(D):
            n = int(lengths[s, d])
            if n:
                buf[d * cols : d * cols + n] = np.frombuffer(
                    streams[s][d], np.uint8
                )
        rows[s] = PaddedSourceRow(buf, cols)
    return rows


def _contig_rows(lengths, streams):
    D = len(streams)
    rows = {}
    for s in range(D):
        offs = row_offsets(lengths[s])
        row = np.empty(int(offs[-1]), np.uint8)
        for d in range(D):
            if lengths[s][d]:
                row[int(offs[d]) : int(offs[d + 1])] = np.frombuffer(
                    streams[s][d], np.uint8
                )
        rows[s] = row
    return rows


@pytest.mark.parametrize("D", [2, 4])
@pytest.mark.parametrize("window_rounds", [0, 2])
def test_exchange_padded_bit_exact(devices, D, window_rounds):
    """Full-shot (window_rounds=0) and windowed-rounds device exchanges
    both reproduce exchange_into byte for byte, with integrity
    verification live."""
    ex = TileExchange(
        make_mesh(D), tile_bytes=1 << 16, verify_integrity=True
    )
    rng = np.random.default_rng(20 + D + window_rounds)
    # payloads span several 64KiB tiles so window_rounds=2 genuinely
    # windows (plan.rounds > 1)
    lengths, streams = _random_plan(rng, D, max_len=90_000)
    ref = ex.exchange_into(lengths, _contig_rows(lengths, streams))
    before = ex.stats()["device_exchanges"]
    out = ex.exchange_padded(
        lengths, _padded_rows(ex, lengths, streams),
        window_rounds=window_rounds,
    )
    assert ex.stats()["device_exchanges"] == before + 1
    for d in range(D):
        view = out[d]
        assert isinstance(view, PaddedDestRowView)
        assert len(view) == D
        for s in range(D):
            got = bytes(memoryview(view[s]))
            assert got == bytes(memoryview(ref[d][s])), (d, s)
            assert got == streams[s][d], (d, s)


def test_exchange_padded_on_round_sequence(devices):
    """The rounds shape reports each landed round in order with the
    plan's [lo, hi) column spans — the overlap hook's contract."""
    D = 2
    ex = TileExchange(make_mesh(D), tile_bytes=1 << 16)
    rng = np.random.default_rng(5)
    lengths, streams = _random_plan(rng, D, max_len=150_000)
    plan = ex.plan(lengths)
    assert plan.rounds > 1, "payload must span multiple tiles"
    events = []

    def on_round(rnd, lo, hi, rows):
        events.append((rnd, lo, hi))
        # delivered rows are already consumable up to hi
        for d in range(D):
            assert rows[d] is not None

    ex.exchange_padded(
        lengths, _padded_rows(ex, lengths, streams),
        on_round=on_round, window_rounds=2,
    )
    assert [e[0] for e in events] == list(range(plan.rounds))
    assert events[0][1] == 0
    assert events[-1][2] == plan.total_cols
    for (_, _, hi_prev), (_, lo, _) in zip(events, events[1:]):
        assert lo == hi_prev


def test_exchange_padded_empty_plan(devices):
    ex = TileExchange(make_mesh(2))
    lengths = np.zeros((2, 2), np.int64)
    out = ex.exchange_padded(lengths, {0: PaddedSourceRow(
        np.empty(0, np.uint8), 0
    )})
    for d in range(2):
        for s in range(2):
            assert bytes(memoryview(out[d][s])) == b""


def test_exchange_padded_rejects_multiprocess(devices, monkeypatch):
    """Multi-host meshes have non-addressable shards; the padded path
    refuses instead of silently corrupting."""
    import jax

    ex = TileExchange(make_mesh(2))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(NotImplementedError):
        ex.exchange_padded(
            np.ones((2, 2), np.int64),
            {0: PaddedSourceRow(np.zeros(2 * 128, np.uint8), 128)},
        )


def test_exchange_padded_integrity_check(devices):
    """verify_integrity on the padded path compares echoed local
    streams and flags corruption."""
    D = 2
    ex = TileExchange(
        make_mesh(D), tile_bytes=1 << 12, verify_integrity=True
    )
    rng = np.random.default_rng(9)
    lengths, streams = _random_plan(rng, D, max_len=500)
    # clean run passes
    ex.exchange_padded(lengths, _padded_rows(ex, lengths, streams))
    # a source row that disagrees with its declared lengths is caught
    # by the echo comparison when we corrupt the row AFTER framing but
    # claim the original stream bytes: simulate by corrupting lengths'
    # implied content via a mismatched row
    rows = _padded_rows(ex, lengths, streams)
    bad = rows[0].buf.copy()
    if int(lengths[0].sum()) == 0:
        pytest.skip("degenerate draw")
    d = int(np.argmax(lengths[0]))
    bad[d * rows[0].cols] ^= 0xFF
    corrupt = dict(rows)
    corrupt[0] = PaddedSourceRow(bad, rows[0].cols)
    got = ex.exchange_padded(lengths, corrupt)
    # the exchange itself is self-consistent (corruption happened
    # before the collective), so the corrupted byte round-trips
    assert bytes(memoryview(got[d][0]))[0] == bad[d * rows[0].cols]


def test_padded_row_views():
    buf = np.arange(20, dtype=np.uint8)
    src = PaddedSourceRow(buf, 10)
    assert src.nbytes == 20
    assert src.stream(0, 4).tolist() == [0, 1, 2, 3]
    assert src.stream(1, 3).tolist() == [10, 11, 12]
    mat = np.arange(12, dtype=np.uint8).reshape(2, 6)
    view = PaddedDestRowView(mat, np.array([4, 2]))
    assert len(view) == 2
    assert view[0].tolist() == [0, 1, 2, 3]
    assert view[1].tolist() == [6, 7]
    assert view.nbytes == 6  # real payload, not the padded matrix


# -- DeviceStagingBridge ------------------------------------------------------

def test_bridge_as_words_alignment():
    row = np.zeros(128, np.uint8)
    words = DeviceStagingBridge.as_words(row)
    assert words is not None and words.dtype == np.uint32
    assert words.nbytes == row.nbytes
    # non-multiple-of-4 byte counts cannot ship as words
    assert DeviceStagingBridge.as_words(np.zeros(9, np.uint8)) is None
    # misaligned base address (offset view into an aligned buffer)
    base = np.zeros(13, np.uint8)
    off = base[1:]
    assert off.nbytes % 4 == 0
    if off.ctypes.data % 4:
        assert DeviceStagingBridge.as_words(off) is None


def test_bridge_to_device_counts_avoided_bytes(devices):
    import jax

    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    try:
        bridge = DeviceStagingBridge()
        row = bridge.alloc_row(256)
        row[:] = np.arange(256, dtype=np.uint8)
        arr = bridge.to_device(
            row, jax.devices()[0], avoided_bytes=row.nbytes
        )
        assert np.array_equal(np.asarray(arr), row)
        snap = GLOBAL_REGISTRY.snapshot()
        vals = {
            c["name"]: c["value"] for c in snap["counters"]
        }
        assert vals.get(
            "device_exchange_h2d_bytes_avoided_total", 0
        ) == 256
    finally:
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()


# -- bucketize_segments -------------------------------------------------------

def test_bucketize_segments_offsets_contract(devices):
    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.ops.partition import (
        bucketize_segments,
        hash_partition_ids,
    )

    keys = jnp.arange(100, dtype=jnp.int32)
    vals = keys * 2
    ids = hash_partition_ids(keys, 4)
    fn = jax.jit(
        bucketize_segments, static_argnames=(
            "n_parts", "capacity", "sort_within"
        )
    )
    (bk, bv), counts, offsets = fn(
        ids, (keys, vals), n_parts=4, capacity=64, sort_within=True
    )
    counts = np.asarray(counts)
    offsets = np.asarray(offsets)
    assert counts.sum() == 100
    # exclusive prefix sum of the clamped counts — the exchange plan's
    # row_offsets contract, computed on device
    assert offsets.tolist() == [0] + np.cumsum(
        np.minimum(counts, 64)
    ).tolist()
    bk, bv = np.asarray(bk), np.asarray(bv)
    for p in range(4):
        n = int(counts[p])
        seg = bk[p, :n]
        assert (np.diff(seg) >= 0).all(), "sort_within broke order"
        # value column rides the key sort consistently
        assert (bv[p, :n] == seg * 2).all()


def test_bucketize_segments_rejects_multidim_sort(devices):
    import jax.numpy as jnp

    from sparkrdma_tpu.ops.partition import bucketize_segments

    keys = jnp.arange(8, dtype=jnp.int32)
    payload = jnp.zeros((8, 3), jnp.int32)
    with pytest.raises(ValueError):
        bucketize_segments(
            keys % 2, (keys, payload), 2, 8, sort_within=True
        )


# -- cluster harness ----------------------------------------------------------

def _cluster(base_port, conf_extra=None, n_exec=2):
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane

    net = LoopbackNetwork()
    overrides = {
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
        "spark.shuffle.tpu.readPlane": "windowed",
    }
    overrides.update(conf_extra or {})
    conf = TpuShuffleConf(overrides)
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(n_exec)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n_exec for e in executors):
            break
        time.sleep(0.01)
    session = None
    if conf.read_plane == "windowed":
        session = BulkShuffleSession(
            TileExchange.from_conf(conf, make_mesh(n_exec)), n_exec,
            timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
            window_rounds=conf.device_exchange_window_rounds,
        )
        for e in executors:
            e.windowed_plane = WindowedReadPlane(e, session=session)
    return net, conf, driver, executors, session


def _write_maps(driver, executors, sid, num_maps, num_parts, seed=0,
                int_records=False, rec_bytes=200, recs_per_map=30):
    rng = np.random.default_rng(seed)
    part = HashPartitioner(num_parts)
    handle = driver.register_shuffle(sid, num_maps, part)
    if int_records:
        records_per_map = [
            [((m * 1000 + j) * 2654435761 % 100003, m * 1000 + j)
             for j in range(recs_per_map)]
            for m in range(num_maps)
        ]
    else:
        records_per_map = [
            [(f"m{m}k{j}", rng.bytes(int(rng.integers(1, rec_bytes))))
             for j in range(recs_per_map)]
            for m in range(num_maps)
        ]
    maps_by_host: dict = {}
    for m, recs in enumerate(records_per_map):
        ex = executors[m % len(executors)]
        w = ex.get_writer(handle, m)
        w.write(recs)
        w.stop(True)
        maps_by_host.setdefault(ex.local_smid, []).append(m)
    return handle, part, records_per_map, maps_by_host


def _read_all_blocks(executors, handle, num_parts):
    E = len(executors)
    out, errs = {}, {}

    def reduce_task(pid):
        try:
            r = executors[pid % E].get_reader(handle, pid, pid + 1, {})
            out[pid] = [
                bytes(memoryview(b)) if not isinstance(b, bytes) else b
                for b in r._iter_block_bytes()
            ]
        except BaseException as e:
            errs[pid] = e

    threads = [
        threading.Thread(target=reduce_task, args=(p,), daemon=True)
        for p in range(num_parts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    return out


def _read_all_records(executors, handle, num_parts, locs=None):
    E = len(executors)
    out, errs = {}, {}

    def reduce_task(pid):
        try:
            r = executors[pid % E].get_reader(
                handle, pid, pid + 1, dict(locs or {})
            )
            out[pid] = sorted(r.read(), key=repr)
        except BaseException as e:
            errs[pid] = e

    threads = [
        threading.Thread(target=reduce_task, args=(p,), daemon=True)
        for p in range(num_parts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    return [out[p] for p in range(num_parts)]


def _run_cluster_records(n_exec, conf_extra, sid, seed,
                         int_records=False):
    net, conf, driver, executors, session = _cluster(
        _ports(), conf_extra, n_exec=n_exec
    )
    try:
        handle, _part, _recs, locs = _write_maps(
            driver, executors, sid, num_maps=4, num_parts=4, seed=seed,
            int_records=int_records,
        )
        recs = _read_all_records(executors, handle, 4, locs=locs)
        dev = session.exchange.stats()["device_exchanges"] if session \
            else 0
        return recs, dev
    finally:
        for m in executors + [driver]:
            m.stop()


# -- off-mode plan-identity pin ----------------------------------------------

def test_off_mode_byte_identical_pin(devices):
    """deviceExchangeEnabled=false routes the identical shuffle through
    the host-staged exchange and yields BYTE-identical block streams —
    the plan-identity pin for the off mode."""
    blocks, dev_counts = {}, {}
    for enabled in ("true", "false"):
        net, conf, driver, executors, session = _cluster(
            _ports(),
            {"spark.shuffle.tpu.deviceExchangeEnabled": enabled},
        )
        try:
            handle, _part, _recs, _locs = _write_maps(
                driver, executors, 700, num_maps=6, num_parts=6,
                seed=77,
            )
            blocks[enabled] = _read_all_blocks(executors, handle, 6)
            dev_counts[enabled] = session.exchange.stats()[
                "device_exchanges"
            ]
        finally:
            for m in executors + [driver]:
                m.stop()
    assert blocks["true"] == blocks["false"]
    assert any(v for v in blocks["true"].values())
    # the toggle genuinely routes: device plane ran only when enabled
    assert dev_counts["true"] > 0
    assert dev_counts["false"] == 0


# -- bit-exact sweep: device vs host-staged vs socket -------------------------

@pytest.mark.parametrize("n_exec", [2, 4])
@pytest.mark.parametrize("mode", ["pickle", "columnar"])
def test_bit_exact_sweep(devices, n_exec, mode):
    """Identical seeded shuffle through the device-native collective,
    the host-staged exchange, and the socket pull reader, across
    decodeThreads {0, 4}: every path returns the same records."""
    ser = {} if mode == "pickle" else {
        "spark.shuffle.tpu.serializer": "columnar"
    }
    planes = {
        "device": {"spark.shuffle.tpu.deviceExchangeEnabled": "true"},
        "host": {"spark.shuffle.tpu.deviceExchangeEnabled": "false"},
        "socket": {"spark.shuffle.tpu.readPlane": "host"},
    }
    sid = 710 + n_exec * 2 + (0 if mode == "pickle" else 1)
    outs, dev_counts = {}, {}
    for plane, extra in planes.items():
        for threads in (0, 4):
            conf_extra = dict(ser)
            conf_extra.update(extra)
            conf_extra["spark.shuffle.tpu.decodeThreads"] = str(threads)
            outs[(plane, threads)], dev_counts[(plane, threads)] = \
                _run_cluster_records(
                    n_exec, conf_extra, sid, seed=13,
                    int_records=(mode == "columnar"),
                )
    ref = outs[("socket", 0)]
    assert any(ref), "reference read returned nothing"
    for key, recs in outs.items():
        assert recs == ref, f"{key} diverged from socket reference"
    assert all(dev_counts[("device", t)] > 0 for t in (0, 4))
    assert all(dev_counts[("host", t)] == 0 for t in (0, 4))


# -- collective/decode overlap ------------------------------------------------

def test_multi_round_overlap_early_delivery(devices, monkeypatch):
    """A multi-round device exchange (small tile, window rounds) emits
    per-round block deliveries while later rounds are still in flight,
    and the records stay bit-exact vs the host-staged path."""
    import sparkrdma_tpu.shuffle.bulk as bulk_mod

    rounds_seen = []
    orig = bulk_mod._make_round_emitter

    def spy(plan, E, me, lengths, sink):
        inner = orig(plan, E, me, lengths, sink)

        def wrapped(rnd, lo, hi, rows):
            rounds_seen.append((me, rnd, lo, hi))
            return inner(rnd, lo, hi, rows)

        return wrapped

    monkeypatch.setattr(bulk_mod, "_make_round_emitter", spy)
    dev_extra = {
        "spark.shuffle.tpu.deviceExchangeEnabled": "true",
        "spark.shuffle.tpu.exchangeTileBytes": str(64 << 10),
        "spark.shuffle.tpu.deviceExchangeWindowRounds": "2",
    }
    host_extra = {
        "spark.shuffle.tpu.deviceExchangeEnabled": "false",
    }
    outs = {}
    for key, extra in (("device", dev_extra), ("host", host_extra)):
        net, conf, driver, executors, session = _cluster(
            _ports(), extra
        )
        try:
            # ~160KiB per source/dest pair stream: several 64KiB rounds
            handle, _part, _recs, _locs = _write_maps(
                driver, executors, 720, num_maps=4, num_parts=2,
                seed=31, rec_bytes=2000, recs_per_map=120,
            )
            outs[key] = _read_all_records(executors, handle, 2)
        finally:
            for m in executors + [driver]:
                m.stop()
    assert outs["device"] == outs["host"]
    assert any(outs["device"])
    # genuine overlap: at least one NON-final round landed early (the
    # emitter defers the last round to the window pump, so any recorded
    # multi-round sequence proves early delivery ran)
    rounds = {r for (_, r, _, _) in rounds_seen}
    assert len(rounds) > 1, (
        f"expected multi-round device exchange, saw rounds {rounds}"
    )


# -- mid-round abort ----------------------------------------------------------

def test_abort_poisons_device_exchange_midround(devices):
    """Poisoning the session while device-exchange windows straggle
    fails every reader promptly (no barrier-timeout ride-out)."""
    from sparkrdma_tpu.shuffle.reader import FetchFailedError

    net, conf, driver, executors, session = _cluster(
        _ports(), {
            "spark.shuffle.tpu.deviceExchangeEnabled": "true",
            "spark.shuffle.tpu.exchangeTileBytes": str(64 << 10),
            "spark.shuffle.tpu.deviceExchangeWindowRounds": "2",
            "spark.shuffle.tpu.bulkPipelineWindows": "true",
        }
    )
    try:
        E = len(executors)
        num_maps, num_parts = 6, 4
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(721, num_maps, part)
        for m in range(3):  # window 0 plannable; windows 1+ straggle
            w = executors[m % E].get_writer(handle, m)
            w.write([(f"m{m}k{j}", j) for j in range(20)])
            w.stop(True)
        results, errors = {}, {}

        def reduce_task(pid):
            try:
                r = executors[pid % E].get_reader(
                    handle, pid, pid + 1, {}
                )
                results[pid] = list(r.read())
            except BaseException as e:
                errors[pid] = e

        threads = [
            threading.Thread(target=reduce_task, args=(p,),
                             daemon=True)
            for p in range(num_parts)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(
                e.windowed_plane.window_events(721) for e in executors
            ):
                break
            time.sleep(0.01)
        assert all(
            e.windowed_plane.window_events(721) for e in executors
        ), "window 0 never exchanged"
        t0 = time.monotonic()
        session.abort(RuntimeError("mid-round participant loss"))
        for t in threads:
            t.join(timeout=20)
        took = time.monotonic() - t0
        assert not any(t.is_alive() for t in threads), "reader hung"
        assert not results, results
        assert set(errors) == set(range(num_parts))
        assert all(
            isinstance(e, FetchFailedError) for e in errors.values()
        ), errors
        assert took < 15, f"abort took {took:.1f}s"
    finally:
        for m in executors + [driver]:
            m.stop()
