"""Seeded chaos sweep over the REAL TCP plane (VERDICT r4 item 4).

The loopback chaos sweep (test_failure_detection.py) injects faults
into an in-process network; real OS processes fail differently —
half-open sockets, partial frames, SIGKILL with no teardown.  This
sweep drives the reference's failure contract
(RdmaShuffleFetcherIterator.scala:368-373 → Spark stage retry) across
genuine process boundaries:

- 3 executor PROCESSES (spawn) serving one-sided reads over sockets,
- per trial, TWO shuffles written and read CONCURRENTLY (reads race
  the writes: location futures fill as publishes land),
- a seeded coin kills one executor with SIGKILL at a random moment —
  sometimes before the writes finish, sometimes mid-stream while a
  multi-hundred-KB block is crossing its socket,
- contract: each shuffle read either completes BIT-EXACT or raises a
  stage-retriable fetch/metadata failure PROMPTLY (no hang), and a
  rerun of the lost work on the survivors completes exactly,
- the victim is replaced by a fresh process (new executor id + port)
  before the next trial — the re-hello path under churn.

``SPARKRDMA_TEST_CHAOS_SEED`` varies the schedule for soak runs; the
default is pinned for CI determinism.  ``SPARKRDMA_TCP_CHAOS_TRIALS``
raises the trial count (default 20 — the sweep stays in `make test`).
"""

import multiprocessing
import os
import random
import threading
import time
from collections import defaultdict

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import (
    FetchFailedError,
    MetadataFetchFailedError,
)
from sparkrdma_tpu.transport import TcpNetwork
from sparkrdma_tpu.utils.types import BlockManagerId, ShuffleManagerId

BASE_PORT = 24200
N_EXEC = 3
NUM_PARTS = 4
ROWS_PER_MAP = 250
VAL_BYTES = 2048


def _conf(driver_port, extra=None):
    d = {
        "spark.shuffle.tpu.driverPort": driver_port,
        # promptness must come from failure detection + connect errors,
        # not from generous timers
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "12s",
        "spark.shuffle.tpu.connectTimeout": "5s",
        "spark.shuffle.tpu.heartbeatInterval": "300ms",
        "spark.shuffle.tpu.heartbeatTimeout": "2s",
    }
    d.update(extra or {})
    return TpuShuffleConf(d)


def _records(sid: int, map_id: int):
    """Deterministic per-(shuffle, map) records — the parent computes
    the oracle without any channel to the children.  Values are KB-
    scale so a SIGKILL can land mid-stream inside one block."""
    rng = random.Random(sid * 7919 + map_id)
    return [
        (f"s{sid}m{map_id}r{j}", bytes([rng.randrange(256)]) * VAL_BYTES)
        for j in range(ROWS_PER_MAP)
    ]


def _executor_proc(idx, exec_id, driver_port, my_port, cmd_q, ack_q,
                   extra_conf=None):
    """Child: one shuffle manager over its own TcpNetwork, driven by
    (op, ...) commands.  SIGKILL can land at ANY point here."""
    try:
        conf = _conf(driver_port, extra_conf)
        ex = TpuShuffleManager(
            conf, is_driver=False, network=TcpNetwork(),
            port=my_port, executor_id=exec_id, stage_to_device=False,
        )
        ack_q.put(("up", exec_id))
        while True:
            cmd = cmd_q.get()
            if cmd[0] == "quit":
                ex.stop()
                ack_q.put(("bye", exec_id))
                return
            if cmd[0] == "write":
                _op, sid, n_maps, map_ids = cmd
                part = HashPartitioner(NUM_PARTS)
                handle = ex.register_shuffle(sid, n_maps, part)
                for m in map_ids:
                    w = ex.get_writer(handle, m)
                    w.write(_records(sid, m))
                    w.stop(True)
                ack_q.put(("wrote", exec_id, sid))
    except BaseException as e:  # surfaced by the parent's ack timeout
        try:
            ack_q.put(("err", exec_id, repr(e)))
        except Exception:
            pass
        raise


class _Cluster:
    """Parent-side handle on the executor processes, with SIGKILL and
    respawn-with-fresh-identity support."""

    def __init__(self, ctx, driver_port, n=N_EXEC, extra_conf=None,
                 base_port=None):
        self.ctx = ctx
        self.driver_port = driver_port
        self.extra_conf = extra_conf
        self._next_port = (base_port if base_port is not None
                           else BASE_PORT + 100)
        self._next_id = 0
        self.procs = {}   # slot -> (proc, exec_id, port, cmd_q)
        self.ack_q = ctx.Queue()
        for slot in range(n):
            self.spawn(slot)

    def spawn(self, slot):
        exec_id = f"c{self._next_id}"
        self._next_id += 1
        port = self._next_port
        self._next_port += 20
        cmd_q = self.ctx.Queue()
        p = self.ctx.Process(
            target=_executor_proc,
            args=(slot, exec_id, self.driver_port, port, cmd_q,
                  self.ack_q, self.extra_conf),
            daemon=True,
        )
        p.start()
        self.procs[slot] = (p, exec_id, port, cmd_q)
        self._await_ack("up", exec_id)

    def _await_ack(self, kind, exec_id, timeout=60):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                msg = self.ack_q.get(timeout=1)
            except Exception:
                continue
            if msg[0] == "err":
                raise AssertionError(f"child {msg[1]} crashed: {msg[2]}")
            if msg[0] == kind and msg[1] == exec_id:
                return
        raise AssertionError(f"no {kind} ack from {exec_id}")

    def smid(self, slot):
        _p, exec_id, port, _q = self.procs[slot]
        return ShuffleManagerId(
            "127.0.0.1", port, BlockManagerId(exec_id, "127.0.0.1", port)
        )

    def order_write(self, slot, sid, n_maps, map_ids):
        self.procs[slot][3].put(("write", sid, n_maps, list(map_ids)))

    def kill(self, slot):
        p = self.procs[slot][0]
        p.kill()
        p.join(timeout=10)

    def stop(self):
        for slot, (p, _e, _po, q) in self.procs.items():
            if p.is_alive():
                try:
                    q.put(("quit",))
                except Exception:
                    pass
        for slot, (p, _e, _po, _q) in self.procs.items():
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()


def _oracle(sid, map_ids):
    out = {}
    for m in map_ids:
        for k, v in _records(sid, m):
            out[k] = v
    return out


def _read_shuffle(driver, handle, maps_by_host, result):
    """Reducer role: read every partition; record exact data or the
    failure.  Runs in a thread so two shuffles read concurrently."""
    t0 = time.monotonic()
    try:
        got = {}
        for pid in range(NUM_PARTS):
            reader = driver.get_reader(handle, pid, pid + 1,
                                       dict(maps_by_host))
            for k, v in reader.read():
                got[k] = v
        result["data"] = got
    except (FetchFailedError, MetadataFetchFailedError) as e:
        result["error"] = e
    result["elapsed"] = time.monotonic() - t0


import pytest


@pytest.mark.parametrize("async_mode,port_off", [
    # offsets keep driver AND driver+50 executor ports inside 24xxx,
    # clear of test_striped_transport (25100-25260) and below the
    # kernel ephemeral range (32768+), so neither fixed-port tests nor
    # lingering ephemeral peer connections can collide
    ("on", 400),    # the completion-driven dispatcher loop
    ("off", 500),   # the legacy thread-per-lane path
])
def test_tcp_chaos_kill_data_channel_mid_striped_read(async_mode,
                                                      port_off):
    """Kill ONE data lane of a striped channel group while a multi-MB
    block is mid-flight across it — on BOTH transport engines: the
    fetch must either complete BIT-EXACT (the stripes raced home
    first) or surface a clean stage-retriable FetchFailedError
    promptly — never hang — and the engine must stay healthy for the
    retry.  Each lane's _fail_outstanding covers its stripes and the
    group combiner fans the first error to the whole fetch."""
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager as Mgr

    driver_port = BASE_PORT + port_off
    conf_d = {
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.transportAsyncDispatcher": async_mode,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "10s",
        "spark.shuffle.tpu.connectTimeout": "5s",
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        "spark.shuffle.tpu.shuffleReadBlockSize": "32m",
        "spark.shuffle.tpu.maxAggBlock": "32m",
        "spark.shuffle.tpu.maxBytesInFlight": "64m",
    }
    driver = Mgr(
        TpuShuffleConf(conf_d), is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    writer_ex = Mgr(
        TpuShuffleConf(conf_d), is_driver=False, network=TcpNetwork(),
        port=driver_port + 50, executor_id="w", stage_to_device=False,
    )
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(writer_ex._peers) < 1:
        time.sleep(0.01)
    try:
        part = HashPartitioner(1)
        handle = driver.register_shuffle(77, 1, part)
        rows = [(f"k{j}", bytes([j % 251]) * 65_536) for j in range(256)]
        w = writer_ex.get_writer(handle, 0)
        w.write(rows)  # one ~16 MB partition → many stripes
        w.stop(True)
        mbh = {writer_ex.local_smid: [0]}

        res: dict = {}

        def read():
            try:
                reader = driver.get_reader(handle, 0, 1, dict(mbh))
                res["data"] = {
                    k: bytes(memoryview(v)) for k, v in reader.read()
                }
            except (FetchFailedError, MetadataFetchFailedError) as e:
                res["error"] = e

        t = threading.Thread(target=read, daemon=True)
        t.start()
        # grab the reader node's channel group to the writer and SIGKILL
        # one data lane mid-read (socket shutdown, no goodbye)
        victim = None
        kill_deadline = time.monotonic() + 10
        while victim is None and time.monotonic() < kill_deadline:
            group = driver.node._read_groups.get(
                (writer_ex.local_smid.host, writer_ex.local_smid.port)
            )
            if group is not None:
                with driver.node._active_lock:
                    active = list(driver.node._active.items())
                lanes = [
                    ch for (_p, _t, slot), ch in active
                    if slot > 0 and ch.is_connected()
                ]
                if lanes:
                    victim = lanes[0]
                    victim.stop()
                    break
            time.sleep(0.0005)
        t.join(timeout=30)
        assert not t.is_alive(), "striped fetch hung after lane kill"
        if "data" in res:
            expected = {k: v for k, v in rows}
            assert res["data"] == expected, "completed fetch not bit-exact"
        else:
            assert isinstance(
                res["error"], (FetchFailedError, MetadataFetchFailedError)
            )
        # the retry path stays healthy: a fresh read completes exactly
        reader2 = driver.get_reader(handle, 0, 1, dict(mbh))
        got2 = {k: bytes(memoryview(v)) for k, v in reader2.read()}
        assert got2 == {k: v for k, v in rows}
    finally:
        writer_ex.stop()
        driver.stop()


def test_tcp_chaos_sigkill_sweep():
    seed = int(os.environ.get("SPARKRDMA_TEST_CHAOS_SEED", "20260731"))
    trials = int(os.environ.get("SPARKRDMA_TCP_CHAOS_TRIALS", "20"))
    rng = random.Random(seed)
    ctx = multiprocessing.get_context("spawn")
    driver_port = BASE_PORT
    driver = TpuShuffleManager(
        _conf(driver_port), is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    cluster = _Cluster(ctx, driver_port)
    part = HashPartitioner(NUM_PARTS)
    stats = defaultdict(int)
    try:
        for trial in range(trials):
            sid_a = 3000 + trial * 10
            sid_b = sid_a + 1
            n_maps = N_EXEC  # one map per executor per shuffle
            ha = driver.register_shuffle(sid_a, n_maps, part)
            hb = driver.register_shuffle(sid_b, n_maps, part)
            mbh = {cluster.smid(s): [s] for s in range(N_EXEC)}
            for s in range(N_EXEC):
                cluster.order_write(s, sid_a, n_maps, [s])
                cluster.order_write(s, sid_b, n_maps, [s])

            kill = trial == 0 or rng.random() < 0.7  # trial 0 always
            victim = rng.randrange(N_EXEC) if kill else None
            # 0..1.5s spans "before the writes land" through "mid-
            # stream during the reads" (each shuffle moves ~1.5 MB)
            delay = rng.uniform(0.0, 1.5) if kill else None
            killer = None
            if kill:
                def _killer(victim=victim, delay=delay):
                    time.sleep(delay)
                    cluster.kill(victim)

                killer = threading.Thread(target=_killer, daemon=True)
                killer.start()

            res_a, res_b = {}, {}
            ra = threading.Thread(
                target=_read_shuffle, args=(driver, ha, mbh, res_a),
                daemon=True,
            )
            rb = threading.Thread(
                target=_read_shuffle, args=(driver, hb, mbh, res_b),
                daemon=True,
            )
            ra.start()
            rb.start()
            ra.join(timeout=90)
            rb.join(timeout=90)
            assert not ra.is_alive() and not rb.is_alive(), (
                f"trial {trial}: reader hung (kill={kill}, "
                f"victim={victim}, delay={delay})"
            )
            if killer is not None:
                killer.join(timeout=30)

            for sid, res in ((sid_a, res_a), (sid_b, res_b)):
                if "data" in res:
                    # completed reads are EXACT, kill or no kill
                    assert res["data"] == _oracle(sid, range(n_maps)), (
                        f"trial {trial} sid {sid}: wrong data "
                        f"(kill={kill}, victim={victim}, delay={delay})"
                    )
                    stats["exact"] += 1
                else:
                    assert kill, (
                        f"trial {trial} sid {sid}: spurious failure "
                        f"with no fault: {res.get('error')}"
                    )
                    # promptness: detection + connect errors, not the
                    # worst-case stack of every timer
                    assert res["elapsed"] < 60, (
                        f"trial {trial} sid {sid}: failure took "
                        f"{res['elapsed']:.1f}s"
                    )
                    stats["failed"] += 1

            if kill:
                # lineage retry on the survivors must complete exactly
                survivors = [s for s in range(N_EXEC) if s != victim]
                retry_sid = sid_a + 5
                hr = driver.register_shuffle(retry_sid, n_maps, part)
                assign = {
                    s: [m for m in range(n_maps)
                        if m % len(survivors) == i]
                    for i, s in enumerate(survivors)
                }
                for s, maps in assign.items():
                    cluster.order_write(s, retry_sid, n_maps, maps)
                mbh_retry = {
                    cluster.smid(s): maps for s, maps in assign.items()
                }
                res_r = {}
                _read_shuffle(driver, hr, mbh_retry, res_r)
                assert res_r.get("data") == _oracle(
                    retry_sid, range(n_maps)
                ), (
                    f"trial {trial}: retry on survivors failed: "
                    f"{res_r.get('error')}"
                )
                stats["retries"] += 1
                # fresh identity replaces the victim (re-hello path)
                cluster.spawn(victim)
        # the sweep must actually have exercised both halves of the
        # contract across the seeded schedule
        assert stats["retries"] >= 3, stats
        assert stats["exact"] >= 3, stats
    finally:
        cluster.stop()
        driver.stop()


def test_tcp_chaos_dead_peer_mid_striped_read_async():
    """SIGKILL the serving executor PROCESS while a striped multi-MB
    read is mid-flight, under transportAsyncDispatcher=on: the read
    fails clean and stage-retriable (or completes exact if the bytes
    raced home), and the reader's dispatcher loop stays healthy — a
    freshly spawned executor serves a rewrite of the lost work
    bit-exact over the SAME driver node."""
    extra = {
        "spark.shuffle.tpu.transportAsyncDispatcher": "on",
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        "spark.shuffle.tpu.shuffleReadBlockSize": "32m",
        "spark.shuffle.tpu.maxAggBlock": "32m",
        "spark.shuffle.tpu.maxBytesInFlight": "64m",
    }
    ctx = multiprocessing.get_context("spawn")
    driver_port = BASE_PORT + 1100
    driver = TpuShuffleManager(
        _conf(driver_port, extra), is_driver=True, network=TcpNetwork(),
        port=driver_port, stage_to_device=False,
    )
    cluster = _Cluster(ctx, driver_port, n=1, extra_conf=extra,
                       base_port=BASE_PORT + 1150)
    part = HashPartitioner(NUM_PARTS)
    try:
        sid = 9100
        handle = driver.register_shuffle(sid, 1, part)
        cluster.order_write(0, sid, 1, [0])
        cluster._await_ack("wrote", cluster.procs[0][1])
        mbh = {cluster.smid(0): [0]}

        res: dict = {}
        t = threading.Thread(
            target=_read_shuffle, args=(driver, handle, mbh, res),
            daemon=True,
        )
        t.start()
        time.sleep(0.02)  # let the striped fetch get airborne
        cluster.kill(0)
        t.join(timeout=60)
        assert not t.is_alive(), "read against SIGKILLed peer hung"
        if "data" in res:
            assert res["data"] == _oracle(sid, [0])
        else:
            assert isinstance(
                res["error"], (FetchFailedError, MetadataFetchFailedError)
            ), res["error"]
            assert res["elapsed"] < 40, res["elapsed"]

        # the dispatcher serves the respawned executor immediately:
        # rewrite the lost work under a fresh shuffle id, read exact
        cluster.spawn(0)
        sid2 = sid + 1
        handle2 = driver.register_shuffle(sid2, 1, part)
        cluster.order_write(0, sid2, 1, [0])
        res2: dict = {}
        _read_shuffle(driver, handle2, {cluster.smid(0): [0]}, res2)
        assert res2.get("data") == _oracle(sid2, [0]), res2.get("error")
    finally:
        cluster.stop()
        driver.stop()
