"""Automatic executor-loss detection: heartbeat + channel-death pruning.

The reference learns about dead peers from RDMA CM DISCONNECTED events
(RdmaNode.java:176-189) and prunes driver state via Spark's
onBlockManagerRemoved listener (RdmaShuffleManager.scala:253-263).
Here the transport has no connection-level death notification, so the
driver runs a heartbeat monitor on the hello/announce plane and treats
control-plane send failures as death signals — nobody ever calls
``remove_executor`` by hand.
"""

import os
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import (
    FetchFailedError,
    MetadataFetchFailedError,
)
from sparkrdma_tpu.transport import LoopbackNetwork


@pytest.fixture()
def cluster(devices):
    """Driver + 3 executors with a FAST heartbeat and a SLOW location
    timeout — failure detection must beat the timeout by a wide margin."""
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 39500,
        "spark.shuffle.tpu.heartbeatInterval": "100ms",
        "spark.shuffle.tpu.heartbeatTimeout": "400ms",
        # promptness must come from detection, not this timer
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "30s",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=39600 + i * 10, executor_id=str(i),
        )
        for i in range(3)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 3 for e in executors):
            break
        time.sleep(0.01)
    yield net, conf, driver, executors
    for m in executors + [driver]:
        m.stop()


def _await(cond, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_heartbeat_keeps_live_executors(cluster):
    net, conf, driver, executors = cluster
    # several heartbeat timeouts pass; acks must keep everyone alive
    time.sleep(1.2)
    assert len(driver.executors) == 3


def test_foreign_shutdown_shaped_error_still_prunes(cluster):
    """Shutdown-vs-failure discrimination must use explicit state, not
    error text: a dead peer channel's pool raises the SAME RuntimeError
    text as our own teardown ("cannot schedule new futures after
    interpreter shutdown").  While the driver is healthy, that error
    must prune the peer — and must NOT quiesce the monitor."""
    net, conf, driver, executors = cluster
    victim = executors[2]
    err = RuntimeError(
        "cannot schedule new futures after interpreter shutdown"
    )
    driver._on_executor_send_failure(victim.local_smid, err)
    assert victim.local_smid not in driver.executors
    assert not driver._hb_stop.is_set(), "monitor wrongly quiesced"
    # the other two executors stay probed and alive
    time.sleep(0.5)
    assert len(driver.executors) == 2


def test_own_node_shutdown_quiesces_instead_of_pruning(cluster):
    """Once OUR node is stopping, a send failure is quiescence: no
    prune, monitor stops.  (Explicit-flag classification — works for
    any error text.)"""
    net, conf, driver, executors = cluster
    driver.node._stopped.set()
    try:
        driver._on_executor_send_failure(
            executors[0].local_smid, OSError("socket closed")
        )
        assert executors[0].local_smid in driver.executors
        assert driver._hb_stop.is_set()
    finally:
        driver.node._stopped.clear()
        driver._hb_stop.clear()


def test_dead_executor_pruned_automatically(cluster):
    net, conf, driver, executors = cluster
    victim = executors[2]
    net.partition(victim.node.address)
    # no manual remove_executor: the monitor's failed send (or missed
    # acks) must prune the victim
    _await(lambda: victim.local_smid not in driver.executors,
           msg="automatic prune of partitioned executor")
    assert len(driver.executors) == 2
    net.heal(victim.node.address)


def test_executor_loss_mid_shuffle_fails_reducer_promptly(cluster):
    """Kill an executor after its maps are CLAIMED but before it
    publishes: the reducer must get a metadata fetch failure from the
    driver's negative answer in seconds, not at the 30s timer."""
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(50, 2, part)
    # executor 0 runs map 0 for real; the victim never runs map 1
    w = executors[0].get_writer(handle, 0)
    w.write([("a", 1)])
    w.stop(True)
    victim = executors[1]
    maps_by_host = {
        executors[0].local_smid: [0],
        victim.local_smid: [1],
    }
    net.partition(victim.node.address)
    t0 = time.monotonic()
    reader = executors[0].get_reader(handle, 0, 2, maps_by_host)
    with pytest.raises(MetadataFetchFailedError):
        list(reader.read())
    elapsed = time.monotonic() - t0
    # detection (≤0.5s) + negative answer, NOT the 30s location timer
    assert elapsed < 10, f"reducer waited {elapsed:.1f}s — not prompt"
    net.heal(victim.node.address)


def test_fetch_status_for_tombstoned_executor_fails_immediately(cluster):
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(51, 1, part)
    victim = executors[1]
    net.partition(victim.node.address)
    _await(lambda: victim.local_smid not in driver.executors,
           msg="prune before fetch")
    t0 = time.monotonic()
    reader = executors[0].get_reader(
        handle, 0, 2, {victim.local_smid: [0]}
    )
    with pytest.raises(MetadataFetchFailedError):
        list(reader.read())
    assert time.monotonic() - t0 < 5
    net.heal(victim.node.address)


def test_unregistered_shuffle_fails_fast(cluster):
    """VERDICT weak #6: the driver used to silently drop fetch-status
    for unknown shuffles, costing requesters the full timeout."""
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    # handle constructed executor-side only: driver never registered 99
    from sparkrdma_tpu.shuffle.manager import ShuffleHandle

    handle = ShuffleHandle(99, 1, part)
    t0 = time.monotonic()
    reader = executors[0].get_reader(
        handle, 0, 1, {executors[1].local_smid: [0]}
    )
    with pytest.raises(MetadataFetchFailedError, match="not registered"):
        list(reader.read())
    assert time.monotonic() - t0 < 5


def _rejoin(net, driver, victim, msg="re-join after heal"):
    """Heal + re-hello a (possibly pruned) executor and await driver
    membership — the rejoin dance a recovered host performs."""
    net.heal(victim.node.address)
    victim._hello_sent = False
    victim._say_hello()
    _await(lambda: victim.local_smid in driver.executors, msg=msg)


def test_pruned_executor_can_rejoin(cluster):
    net, conf, driver, executors = cluster
    victim = executors[2]
    net.partition(victim.node.address)
    _await(lambda: victim.local_smid not in driver.executors,
           msg="prune")
    _rejoin(net, driver, victim)


def test_loss_after_publish_still_fails_data_plane(cluster):
    """Locations resolve (publish completed) but the data fetch hits the
    dead transport: FetchFailedError, also prompt."""
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(52, 2, part)
    maps_by_host = defaultdict(list)
    for map_id in range(2):
        ex = executors[map_id]
        w = ex.get_writer(handle, map_id)
        w.write([(f"k{map_id}", map_id)])
        w.stop(True)
        maps_by_host[ex.local_smid].append(map_id)
    _await(lambda: sum(len(v) for v in driver.maps_by_host(52).values()) == 2,
           msg="publishes to land")
    victim = executors[1]
    net.partition(victim.node.address)
    t0 = time.monotonic()
    reader = executors[0].get_reader(handle, 0, 2, dict(maps_by_host))
    with pytest.raises(FetchFailedError):
        list(reader.read())
    assert time.monotonic() - t0 < 10
    net.heal(victim.node.address)


def test_executor_loss_fails_bulk_plan_waiters_promptly(cluster):
    """Bulk mode needs stable membership: losing a member while plan
    requests are pending must answer them negatively immediately."""
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.bulk import BulkExchangeReader

    net, conf, driver, executors = cluster
    part = HashPartitioner(4)
    handle = driver.register_shuffle(55, 2, part)
    # only map 0 publishes; the victim never runs map 1, so the plan
    # barrier cannot pass until failure detection kicks in
    w = executors[0].get_writer(handle, 0)
    w.write([("a", 1)])
    w.stop(True)
    victim = executors[2]
    reader = BulkExchangeReader(
        executors[0], TileExchange(make_mesh(3), tile_bytes=1 << 12)
    )
    t0 = time.monotonic()
    import threading

    box = {}

    def run():
        try:
            box["out"] = list(reader.read(55))
        except BaseException as e:
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not box, "plan answered before the barrier could pass"
    net.partition(victim.node.address)
    t.join(timeout=15)
    assert "err" in box, box
    assert isinstance(box["err"], MetadataFetchFailedError)
    assert time.monotonic() - t0 < 15
    net.heal(victim.node.address)


def test_post_loss_bulk_plan_request_fails_fast(cluster):
    """A plan request arriving AFTER the loss (maps pruned, barrier can
    never pass again) must fail immediately via the membership epoch,
    not ride out the location timeout."""
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.bulk import BulkExchangeReader

    net, conf, driver, executors = cluster
    part = HashPartitioner(4)
    handle = driver.register_shuffle(56, 2, part)
    for m in range(2):
        w = executors[m].get_writer(handle, m)
        w.write([(f"k{m}", m)])
        w.stop(True)
    victim = executors[1]
    net.partition(victim.node.address)
    _await(lambda: victim.local_smid not in driver.executors,
           msg="prune")
    # request arrives only AFTER the removal
    reader = BulkExchangeReader(
        executors[0], TileExchange(make_mesh(3), tile_bytes=1 << 12)
    )
    t0 = time.monotonic()
    with pytest.raises(MetadataFetchFailedError, match="membership"):
        list(reader.read(56))
    assert time.monotonic() - t0 < 5
    net.heal(victim.node.address)


def test_duplicate_prune_does_not_bump_epoch(cluster):
    """A heartbeat-timeout prune racing a send-failure callback calls
    remove_executor twice; the second call must not bump the membership
    epoch (it would doom shuffles registered after the first prune) nor
    re-clear plan waiters/cache (code-review finding)."""
    net, conf, driver, executors = cluster
    victim = executors[2]
    net.partition(victim.node.address)
    _await(lambda: victim.local_smid not in driver.executors, msg="prune")
    epoch = driver._membership_epoch
    driver.remove_executor(victim.local_smid)  # duplicate (raced) prune
    assert driver._membership_epoch == epoch
    net.heal(victim.node.address)


def test_publish_from_tombstoned_executor_dropped(cluster):
    """An in-flight publish racing its executor's prune must not
    resurrect the dead executor's outputs on the driver."""
    from sparkrdma_tpu.rpc.messages import PublishMapTaskOutputMsg
    from sparkrdma_tpu.shuffle.map_output import MapTaskOutput

    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    driver.register_shuffle(77, 1, part)
    victim = executors[0]
    net.partition(victim.node.address)
    _await(lambda: victim.local_smid not in driver.executors, msg="prune")
    from sparkrdma_tpu.utils.types import BlockLocation

    mto = MapTaskOutput(2)
    mto.put(0, BlockLocation(1, 8, 3))
    mto.put(1, BlockLocation(9, 8, 3))
    msg = PublishMapTaskOutputMsg(
        victim.local_smid, shuffle_id=77, map_id=0,
        total_num_partitions=2, first_reduce_id=0, last_reduce_id=1,
        entries=mto.get_range_bytes(0, 1),
    )
    driver._handle_publish(msg)
    assert victim.local_smid not in driver.maps_by_host(77)
    net.heal(victim.node.address)


def test_chaos_random_faults_exact_or_clean_failure(cluster):
    """Randomized fault sweep over the reduce phase: whatever the
    timing, a job must end in EXACTLY one of two states — bit-exact
    results, or a stage-retriable fetch/metadata failure followed by
    a successful retry on the survivors.  Wrong data or a hang is a
    bug (the reference leans on the same contract:
    RdmaShuffleFetcherIterator.scala:368-373 → Spark stage retry)."""
    import random
    import threading
    from collections import defaultdict

    from tests.test_shuffle_e2e import run_maps

    net, conf, driver, executors = cluster
    # SPARKRDMA_TEST_CHAOS_SEED varies the schedule for soak runs
    # (default pinned for CI determinism)
    rng = random.Random(int(os.environ.get(
        "SPARKRDMA_TEST_CHAOS_SEED", "1234"
    )))
    t_start = time.monotonic()
    retries_proven = 0
    for trial in range(8):
        sid = 900 + trial * 2
        P = rng.choice([2, 4])
        n_maps = rng.choice([3, 6])
        handle = driver.register_shuffle(sid, n_maps, HashPartitioner(P))
        records_per_map = [
            [(rng.randrange(30), rng.randrange(100))
             for _ in range(rng.randrange(50, 200))]
            for _ in range(n_maps)
        ]
        maps_by_host = run_maps(handle, executors, records_per_map)
        oracle = defaultdict(list)
        for recs in records_per_map:
            for k, v in recs:
                oracle[k].append(v)

        # trial 0 is a guaranteed pre-read partition so the
        # failure->retry half of the contract is ALWAYS exercised;
        # later trials race the injection against the reads.
        # "channel": flip ONE live channel toward the victim into
        # sticky ERROR (a QP death without a network partition) —
        # the transport must reconnect or fail cleanly, never corrupt
        fault = ("partition" if trial == 0
                 else rng.choice(["none", "partition", "partition",
                                  "channel"]))
        victim = rng.choice(executors[1:])  # reader is executor 0
        delay = 0.0 if trial == 0 else rng.uniform(0.0, 0.008)
        injected = threading.Event()

        def inject(victim=victim, delay=delay, fault=fault):
            time.sleep(delay)
            if fault == "partition":
                net.partition(victim.node.address)
            elif fault == "channel":
                with victim.node._active_lock:
                    chans = list(victim.node._active.values())
                if chans:
                    rng.choice(chans).inject_error()
            injected.set()

        th = threading.Thread(target=inject, daemon=True)
        th.start()
        got = defaultdict(list)
        failed = None
        try:
            for pid in range(P):
                reader = executors[0].get_reader(
                    handle, pid, pid + 1, maps_by_host
                )
                for k, v in reader.read():
                    got[k].append(v)
        except (FetchFailedError, MetadataFetchFailedError) as e:
            failed = e
        th.join(timeout=5)
        assert injected.is_set()
        if failed is None:
            # whatever the fault timing, completed results are EXACT
            assert set(got) == set(oracle), (trial, fault)
            for k in oracle:
                assert sorted(got[k]) == sorted(oracle[k]), (trial, k)
        else:
            # a channel error may fail the read (acceptable — it is a
            # QP death) or be absorbed by a reconnect; a partition may
            # fail it; fault=none must never fail
            assert fault in ("partition", "channel"), (
                f"spurious failure: {failed}"
            )
            # the lineage contract: heal, re-register, rerun on the
            # survivors, and the retry must complete exactly
            net.heal(victim.node.address)
            survivors = [e for e in executors if e is not victim]
            retry = driver.register_shuffle(
                sid + 1, n_maps, HashPartitioner(P)
            )
            retry_maps = run_maps(retry, survivors, records_per_map)
            regot = defaultdict(list)
            for pid in range(P):
                reader = executors[0].get_reader(
                    retry, pid, pid + 1, retry_maps
                )
                for k, v in reader.read():
                    regot[k].append(v)
            assert set(regot) == set(oracle), trial
            for k in oracle:
                assert sorted(regot[k]) == sorted(oracle[k]), (trial, k)
            retries_proven += 1
        driver.unregister_shuffle(sid)
        driver.unregister_shuffle(sid + 1)
        # restore full membership for the next trial.  The rejoin is
        # UNCONDITIONAL after a partition: a heartbeat prune can land
        # asynchronously after a membership check, so checking first
        # would race it and poison the next trial
        net.heal(victim.node.address)
        if fault in ("partition", "channel"):
            time.sleep(0.05)  # let any in-flight prune drain
            _rejoin(net, driver, victim, msg=f"trial {trial} rejoin")
    assert retries_proven >= 1  # trial 0 guarantees the retry path ran
    # the sweep must not stall: 8 trials incl. retries, well under the
    # per-trial timers (a hang would blow this by minutes)
    assert time.monotonic() - t_start < 120


def test_rejoin_hello_refreshes_ack_clock(cluster):
    """A re-hello from a healed executor must refresh the heartbeat
    ack clock: with a stale pre-partition timestamp surviving the
    hello (the setdefault bug the chaos sweep found), the monitor's
    next sweep re-prunes the executor before its first fresh ack."""
    net, conf, driver, executors = cluster
    victim = executors[2]
    for rep in range(5):
        # stale clock + immediate re-hello, as after a short partition
        # the monitor never noticed.  The artificial backdating holds
        # the prune window open for the whole hello RPC (in production
        # it is microseconds), so a sweep can prune mid-attempt; that
        # benign ordering self-heals via rejoin — retry the attempt
        for attempt in range(3):
            t0 = time.monotonic()
            driver._last_ack[victim.local_smid] = t0 - 10.0
            victim._hello_sent = False
            victim._say_hello()
            # synchronize on the driver-side clock actually moving
            # past the injected stale value (membership alone is
            # already true and would not prove the hello landed)
            _await(
                lambda: driver._last_ack.get(victim.local_smid, 0.0)
                >= t0 - 5.0,
                msg=f"rep {rep} ack-clock refresh",
            )
            if victim.local_smid in driver.executors:
                break
        # outlive a few monitor sweeps (interval 100ms, timeout 400ms)
        time.sleep(0.25)
        assert victim.local_smid in driver.executors, (
            f"rep {rep}: healed executor re-pruned off a stale ack clock"
        )
