"""Bulk-synchronous collective shuffle (shuffle/bulk.py): map phase →
plan barrier → ONE symmetric exchange → consume.

Single-process here (loopback control plane, multi-device mesh, a
BulkShuffleSession as the in-process contribution barrier); the
cross-PROCESS version runs inside tests/multihost_worker.py over a real
TCP control plane and a multi-controller mesh, where the collective
itself is the barrier.
"""

import threading
import time

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.parallel.exchange import TileExchange
from sparkrdma_tpu.parallel.mesh import make_mesh
from sparkrdma_tpu.shuffle.bulk import BulkExchangeReader, BulkShuffleSession
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import MetadataFetchFailedError
from sparkrdma_tpu.transport import LoopbackNetwork


@pytest.fixture()
def cluster(devices):
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 43500,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=43600 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(3)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 3 for e in executors):
            break
        time.sleep(0.01)
    yield net, conf, driver, executors
    for m in executors + [driver]:
        m.stop()


def _bulk_read_all(executors, shuffle_id, mesh):
    """All hosts read concurrently through one shared session (the
    in-process stand-in for per-process collective participation)."""
    session = BulkShuffleSession(
        TileExchange(mesh, tile_bytes=1 << 12), len(executors)
    )
    results = {}
    errors = {}

    def run(e):
        try:
            results[e.executor_id] = list(
                BulkExchangeReader(e, session=session).read(shuffle_id)
            )
        except BaseException as err:
            errors[e.executor_id] = err

    threads = [
        threading.Thread(target=run, args=(e,), daemon=True)
        for e in executors
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    return results


def test_bulk_shuffle_e2e(cluster):
    net, conf, driver, executors = cluster
    E = len(executors)
    num_maps, num_parts = 6, 9
    part = HashPartitioner(num_parts)
    handle = driver.register_shuffle(60, num_maps, part)
    records_per_map = [
        [(f"k{j}", (m, j)) for j in range(40)] for m in range(num_maps)
    ]
    for m, records in enumerate(records_per_map):
        w = executors[m % E].get_writer(handle, m)
        w.write(records)
        w.stop(True)

    results = _bulk_read_all(executors, 60, make_mesh(E))

    # canonical host order = sorted by (host, port); every record landed
    # on the host owning its partition, and nothing was lost
    hosts = sorted(
        (e.local_smid for e in executors), key=lambda s: (s.host, s.port)
    )
    got = []
    for e in executors:
        mine = results[e.executor_id]
        my_index = hosts.index(e.local_smid)
        for k, _v in mine:
            assert part.partition(k) % E == my_index
        got.extend(mine)
    expect = [kv for recs in records_per_map for kv in recs]
    assert sorted(map(repr, got)) == sorted(map(repr, expect))


def test_bulk_plan_unregistered_shuffle_fails_fast(cluster):
    net, conf, driver, executors = cluster
    reader = BulkExchangeReader(
        executors[0], TileExchange(make_mesh(3), tile_bytes=1 << 12)
    )
    t0 = time.monotonic()
    with pytest.raises(MetadataFetchFailedError, match="not registered"):
        list(reader.read(999))
    assert time.monotonic() - t0 < 5


def test_bulk_plan_waits_for_all_maps(cluster):
    """The plan is a BARRIER: it must not answer until every registered
    map published."""
    net, conf, driver, executors = cluster
    part = HashPartitioner(6)
    handle = driver.register_shuffle(61, 2, part)
    w = executors[0].get_writer(handle, 0)
    w.write([("a", 1)])
    w.stop(True)
    # map 1 not yet written: the plan request must stay pending
    box = {}
    mesh = make_mesh(3)

    def run():
        try:
            box["out"] = _bulk_read_all(executors, 61, mesh)
        except BaseException as e:
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)
    assert not box, "plan answered before all maps published"
    w = executors[1].get_writer(handle, 1)
    w.write([("b", 2)])
    w.stop(True)
    t.join(timeout=60)
    assert "out" in box, box.get("err")
    got = [kv for mine in box["out"].values() for kv in mine]
    assert sorted(got) == [("a", 1), ("b", 2)]


def test_bulk_empty_partitions(cluster):
    net, conf, driver, executors = cluster
    part = HashPartitioner(7)
    handle = driver.register_shuffle(62, 2, part)
    for m, recs in enumerate([[], [("x", 1)]]):
        w = executors[m].get_writer(handle, m)
        w.write(recs)
        w.stop(True)
    results = _bulk_read_all(executors, 62, make_mesh(3))
    got = [kv for mine in results.values() for kv in mine]
    assert got == [("x", 1)]


def test_bulk_read_plane_via_context(devices):
    """readPlane=bulk through the high-level Dataset API: wide ops run
    the map phase normally, then ONE plan barrier + ONE symmetric
    collective replaces the per-partition pull readers."""
    from sparkrdma_tpu.api import TpuShuffleContext

    data = [(i % 17, i) for i in range(3000)]

    def run(conf, port):
        with TpuShuffleContext(
            num_executors=3, conf=conf, base_port=port,
            stage_to_device=False,
        ) as ctx:
            ds = ctx.parallelize(data, num_slices=6)
            return (
                sorted(
                    ds.reduce_by_key(lambda a, b: a + b, num_partitions=6)
                    .collect()
                ),
                sorted(ds.sort_by_key(num_partitions=6).collect()),
            )

    bulk_conf = TpuShuffleConf()
    bulk_conf.set("readPlane", "bulk")
    host = run(TpuShuffleConf(), 44500)
    bulk = run(bulk_conf, 44700)
    assert host == bulk


def test_bulk_columnar_fast_path(devices):
    """serializer=columnar + readPlane=bulk keeps the vectorized
    columnar read-side kernels (no per-record Python loop)."""
    import numpy as np

    from sparkrdma_tpu.api import TpuShuffleContext

    conf = TpuShuffleConf()
    conf.set("readPlane", "bulk")
    conf.set("serializer", "columnar")
    with TpuShuffleContext(
        num_executors=3, conf=conf, base_port=45200,
        stage_to_device=False,
    ) as ctx:
        n = 5000
        keys = np.arange(n, dtype=np.int64) % 97
        vals = np.arange(n, dtype=np.int64)
        got = dict(
            ctx.parallelize_columns(keys, vals, num_slices=6)
            .reduce_by_key("sum", num_partitions=6)
            .collect()
        )
        expect = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            expect[k] = expect.get(k, 0) + v
        assert got == expect


def test_bulk_session_abort_unblocks_waiters():
    """A participant failing before contribution poisons the barrier —
    waiters fail immediately, not at the 120s timeout."""
    import numpy as np

    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.bulk import BulkShuffleSession

    session = BulkShuffleSession(
        TileExchange(make_mesh(2), tile_bytes=1 << 12), 2
    )
    lengths = np.zeros((2, 2), np.int64)
    box = {}

    def waiter():
        try:
            session.run(0, [b"", b""], lengths)
        except BaseException as e:
            box["err"] = e

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.2)
    session.abort(RuntimeError("participant 1 exploded"))
    t.join(timeout=10)
    assert not t.is_alive(), "waiter still blocked after abort"
    assert "participant 1 exploded" in repr(box["err"].__cause__)
    # the poison is sticky for late contributors too
    with pytest.raises(RuntimeError, match="aborted"):
        session.run(1, [b"", b""], lengths)


def test_bulk_concurrent_shuffles(cluster):
    """Two bulk shuffles in flight at once: per-shuffle plan waiters,
    caches, and sessions must not cross."""
    net, conf, driver, executors = cluster
    E = len(executors)
    mesh = make_mesh(E)
    handles = {}
    for sid, nparts in ((63, 5), (64, 8)):
        part = HashPartitioner(nparts)
        handles[sid] = driver.register_shuffle(sid, E, part)
        for m in range(E):
            w = executors[m].get_writer(handles[sid], m)
            w.write([((sid, f"k{m}-{j}"), j) for j in range(25)])
            w.stop(True)

    out = {}
    errs = {}

    def run(sid):
        try:
            out[sid] = _bulk_read_all(executors, sid, mesh)
        except BaseException as e:
            errs[sid] = e

    threads = [
        threading.Thread(target=run, args=(sid,), daemon=True)
        for sid in handles
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    for sid in handles:
        got = sorted(
            kv for mine in out[sid].values() for kv in mine
        )
        want = sorted(
            ((sid, f"k{m}-{j}"), j)
            for m in range(E) for j in range(25)
        )
        assert got == want, sid


def test_bulk_matches_pull_fuzz(cluster):
    """Seeded equivalence: random shuffles produce identical results on
    the bulk plane and the per-partition pull readers."""
    import random

    net, conf, driver, executors = cluster
    E = len(executors)
    mesh = make_mesh(E)
    rng = random.Random(11)
    for trial in range(4):
        sid = 70 + trial
        num_maps = rng.randint(1, 5)
        nparts = rng.randint(1, 10)
        part = HashPartitioner(nparts)
        handle = driver.register_shuffle(sid, num_maps, part)
        records_per_map = [
            [(rng.randint(0, 20), rng.random()) for _ in
             range(rng.randint(0, 60))]
            for _ in range(num_maps)
        ]
        maps_by_host = {}
        for m, recs in enumerate(records_per_map):
            ex = executors[m % E]
            w = ex.get_writer(handle, m)
            w.write(recs)
            w.stop(True)
            maps_by_host.setdefault(ex.local_smid, []).append(m)

        bulk = sorted(
            kv
            for mine in _bulk_read_all(executors, sid, mesh).values()
            for kv in mine
        )
        pull = []
        for p in range(nparts):
            reader = executors[p % E].get_reader(
                handle, p, p + 1, maps_by_host
            )
            pull.extend(reader.read())
        assert bulk == sorted(pull), (trial, sid)


def test_publish_before_hello_waits_for_membership(devices):
    """A map output can publish before its executor's hello lands
    (separate channels): the plan barrier must WAIT for the hello
    instead of failing the stage (flaky dryrun race)."""
    import numpy as np

    from sparkrdma_tpu.rpc.messages import PublishMapTaskOutputMsg
    from sparkrdma_tpu.shuffle.manager import _PLAN_WAIT
    from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
    from sparkrdma_tpu.utils.types import (
        BlockLocation,
        BlockManagerId,
        ShuffleManagerId,
    )

    net = LoopbackNetwork()
    conf = TpuShuffleConf({"spark.shuffle.tpu.driverPort": 39750})
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    try:
        ghost = ShuffleManagerId("127.0.0.1", 49777,
                                 BlockManagerId("g", "127.0.0.1", 49777))
        driver._shuffle_num_maps[90] = 1
        driver._shuffle_partitions[90] = 2
        with driver._plan_lock:
            driver._shuffle_epoch[90] = driver._membership_epoch
        mto = MapTaskOutput(2)
        mto.put(0, BlockLocation(0, 8, 1))
        mto.put(1, BlockLocation(8, 8, 1))
        msg = PublishMapTaskOutputMsg(
            ghost, shuffle_id=90, map_id=0, total_num_partitions=2,
            first_reduce_id=0, last_reduce_id=1,
            entries=mto.get_range_bytes(0, 1),
        )
        driver._handle_publish(msg)  # publish BEFORE any hello
        plan = driver._get_or_build_plan(90, 1)
        assert plan is _PLAN_WAIT, plan
        # hello lands → the same barrier now builds a real plan
        with driver._executors_lock:
            driver._executors.append(ghost)
        plan2 = driver._get_or_build_plan(90, 1)
        assert not isinstance(plan2, str) and plan2 is not _PLAN_WAIT
        hosts, flat, manifest, idx = plan2
        assert ghost in idx and np.asarray(flat).sum() == 16
    finally:
        driver.stop()


# -- incremental (windowed) plans -------------------------------------------

def _windowed_cluster(window_maps, base_port):
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
        "spark.shuffle.tpu.bulkWindowMaps": str(window_maps),
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(3)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 3 for e in executors):
            break
        time.sleep(0.01)
    return net, conf, driver, executors


def _windowed_read_all(executors, shuffle_id, mesh, conf):
    session = BulkShuffleSession(
        TileExchange(mesh, tile_bytes=1 << 12), len(executors),
        timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
    )
    readers = {
        e.executor_id: BulkExchangeReader(e, session=session)
        for e in executors
    }
    results = {}
    errors = {}

    def run(e):
        try:
            results[e.executor_id] = list(
                readers[e.executor_id].read(shuffle_id)
            )
        except BaseException as err:
            errors[e.executor_id] = err

    threads = [
        threading.Thread(target=run, args=(e,), daemon=True)
        for e in executors
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    return results, readers


def test_bulk_windowed_e2e(devices):
    """bulkWindowMaps=2 with 6 maps → 3 incremental plan windows, all
    records arriving exactly as in the single-barrier mode."""
    net, conf, driver, executors = _windowed_cluster(2, 44500)
    try:
        E = len(executors)
        num_maps, num_parts = 6, 9
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(61, num_maps, part)
        records_per_map = [
            [(f"k{j}", (m, j)) for j in range(40)] for m in range(num_maps)
        ]
        for m, records in enumerate(records_per_map):
            w = executors[m % E].get_writer(handle, m)
            w.write(records)
            w.stop(True)

        results, readers = _windowed_read_all(
            executors, 61, make_mesh(E), conf
        )
        hosts = sorted(
            (e.local_smid for e in executors),
            key=lambda s: (s.host, s.port),
        )
        got = []
        for e in executors:
            mine = results[e.executor_id]
            my_index = hosts.index(e.local_smid)
            for k, _v in mine:
                assert part.partition(k) % E == my_index
            got.extend(mine)
        expect = [kv for recs in records_per_map for kv in recs]
        assert sorted(map(repr, got)) == sorted(map(repr, expect))
        # 6 maps / window of 2 → exactly 3 window exchanges per host
        for e in executors:
            events = readers[e.executor_id].window_events
            assert [w for w, _t, _b in events] == [0, 1, 2], events
    finally:
        for m in executors + [driver]:
            m.stop()


def test_bulk_windowed_overlaps_straggler_map(devices):
    """The overlap contract (VERDICT r2 item 4 / reference
    RdmaShuffleFetcherIterator.scala:241-251): reducers receive window-0
    bytes while the last map has not even been WRITTEN yet."""
    net, conf, driver, executors = _windowed_cluster(2, 44900)
    try:
        E = len(executors)
        num_maps, num_parts = 4, 6
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(62, num_maps, part)
        records_per_map = [
            [(f"k{j}", (m, j)) for j in range(30)] for m in range(num_maps)
        ]
        # write only the first 3 maps (window 0 = 2 maps can be planned)
        for m in range(3):
            w = executors[m % E].get_writer(handle, m)
            w.write(records_per_map[m])
            w.stop(True)

        session = BulkShuffleSession(
            TileExchange(make_mesh(E), tile_bytes=1 << 12), E,
            timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
        )
        readers = {
            e.executor_id: BulkExchangeReader(e, session=session)
            for e in executors
        }
        results = {}
        errors = {}

        def run(e):
            try:
                results[e.executor_id] = list(
                    readers[e.executor_id].read(62)
                )
            except BaseException as err:
                errors[e.executor_id] = err

        threads = [
            threading.Thread(target=run, args=(e,), daemon=True)
            for e in executors
        ]
        for t in threads:
            t.start()

        # window 0 must complete while map 3 is still unwritten
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(r.window_events for r in readers.values()):
                break
            time.sleep(0.01)
        assert all(r.window_events for r in readers.values()), (
            "no window exchanged before the straggler map published"
        )
        t_first_window = max(
            r.window_events[0][1] for r in readers.values()
        )
        assert not results, "read() returned before the last map"

        t_straggler = time.monotonic()
        assert t_first_window < t_straggler
        w = executors[3 % E].get_writer(handle, 3)
        w.write(records_per_map[3])
        w.stop(True)

        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        got = [kv for r in results.values() for kv in r]
        expect = [kv for recs in records_per_map for kv in recs]
        assert sorted(map(repr, got)) == sorted(map(repr, expect))
    finally:
        for m in executors + [driver]:
            m.stop()


def test_bulk_session_timeout_conf():
    """The in-process barrier honors the conf-driven timeout instead
    of the old hardcoded 120s."""
    import numpy as np

    session = BulkShuffleSession(
        TileExchange(make_mesh(2), tile_bytes=1 << 12), 2, timeout_s=0.2
    )
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="bulkBarrierTimeout"):
        session.run(0, [b"", b""], np.zeros((2, 2), np.int64))
    assert time.monotonic() - t0 < 5


def test_bulk_windowed_zero_map_shuffle(devices):
    """A zero-map shuffle (empty upstream stage) completes with no
    records in windowed mode, like the legacy full-barrier path."""
    net, conf, driver, executors = _windowed_cluster(2, 45300)
    try:
        part = HashPartitioner(4)
        driver.register_shuffle(63, 0, part)
        results, readers = _windowed_read_all(
            executors, 63, make_mesh(len(executors)), conf
        )
        assert all(v == [] for v in results.values()), results
        for r in readers.values():
            assert [w for w, _t, _b in r.window_events] == [0]
    finally:
        for m in executors + [driver]:
            m.stop()


def test_bulk_mixed_plan_modes_rejected(devices):
    """Conf skew (one host windowed, one full-barrier) must fail fast
    instead of hanging the shared collective to the barrier timeout."""
    net, conf, driver, executors = _windowed_cluster(2, 45700)
    try:
        part = HashPartitioner(4)
        handle = driver.register_shuffle(64, len(executors), part)
        for m, e in enumerate(executors):
            w = e.get_writer(handle, m)
            w.write([(f"k{j}", j) for j in range(10)])
            w.stop(True)
        session = BulkShuffleSession(
            TileExchange(make_mesh(len(executors)), tile_bytes=1 << 12),
            len(executors),
        )
        # first reader establishes windowed mode...
        r0 = BulkExchangeReader(executors[0], session=session)
        results = {}

        def _r0_read():
            # r0 is expected to fail too once the skewed request dooms
            # the shuffle (the teardown abort below wakes its barrier
            # wait) — catch in-thread so pytest's unhandled-thread-
            # exception warning stays meaningful for real leaks
            try:
                results["ok"] = list(r0.read(64))
            except (MetadataFetchFailedError, RuntimeError,
                    TimeoutError) as e:
                results["r0_err"] = e

        t0 = threading.Thread(target=_r0_read, daemon=True)
        t0.start()
        time.sleep(0.3)  # let its windowed request land first
        # ...then a full-barrier request (skewed conf) must fail fast
        legacy_conf = TpuShuffleConf({
            "spark.shuffle.tpu.driverPort": conf.driver_port,
        })
        ex1 = executors[1]
        old = ex1.conf
        ex1.conf = legacy_conf
        try:
            r1 = BulkExchangeReader(ex1, session=session)
            t_start = time.monotonic()
            with pytest.raises(
                MetadataFetchFailedError, match="plan mode mismatch"
            ):
                list(r1.read(64))
            assert time.monotonic() - t_start < 10
        finally:
            ex1.conf = old
            # r0 may be parked in its round's contribution barrier
            # (its partner never contributes): abort so the thread
            # exits NOW instead of riding out the 120s timeout past
            # the test
            session.abort(RuntimeError("mode-mismatch test teardown"))
            t0.join(timeout=10)
            assert not t0.is_alive(), "r0 reader thread leaked"
            assert "ok" not in results, results
            # r0 must have failed for one of the EXPECTED reasons (the
            # doomed shuffle or the teardown abort), not something else
            err = results.get("r0_err")
            assert err is not None and (
                isinstance(err, MetadataFetchFailedError)
                or "mode-mismatch test teardown" in str(
                    getattr(err, "__cause__", None) or err
                )
            ), repr(err)
    finally:
        for m in executors + [driver]:
            m.stop()

# -- unified reactive device plane (readPlane=windowed) ----------------------
# Reducers issue partition reads through manager.get_reader and the
# bytes move via driver-planned window collectives: reactive like the
# reference's fetcher iterator (RdmaShuffleFetcherIterator.scala:241-251)
# AND multi-process like the bulk plane (the cross-process version runs
# in tests/multihost_worker.py).


def _windowed_plane_cluster(window_maps, base_port, n_exec=2):
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane

    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
        "spark.shuffle.tpu.bulkWindowMaps": str(window_maps),
        "spark.shuffle.tpu.readPlane": "windowed",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(n_exec)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n_exec for e in executors):
            break
        time.sleep(0.01)
    session = BulkShuffleSession(
        TileExchange(make_mesh(n_exec), tile_bytes=1 << 12), n_exec,
        timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
    )
    for e in executors:
        e.windowed_plane = WindowedReadPlane(e, session=session)
    return net, conf, driver, executors


def test_windowed_plane_reactive_reader_overlap(devices):
    """The unified-plane contract (VERDICT r3 item 3): a REDUCER-issued
    read yields window-0 block payloads while the straggler map has not
    been written, then completes once it lands."""
    net, conf, driver, executors = _windowed_plane_cluster(2, 46200)
    try:
        E = len(executors)
        num_maps, num_parts = 4, 6
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(66, num_maps, part)
        records_per_map = [
            [(f"m{m}k{j}", (m, j)) for j in range(60)]
            for m in range(num_maps)
        ]
        for m in range(3):  # window 0 (2 maps) can be planned
            w = executors[m % E].get_writer(handle, m)
            w.write(records_per_map[m])
            w.stop(True)

        # partition 0 belongs to executor 0 (0 % 2); its reader is the
        # reactive observer.  Executor 1 joins the collectives.
        executors[1].windowed_plane.join(66)
        r0 = executors[0].get_reader(handle, 0, 1, {})
        blocks = []
        finished = threading.Event()

        def consume():
            for data in r0._iter_block_bytes():
                blocks.append((time.monotonic(), bytes(data)))
            finished.set()

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not blocks:
            time.sleep(0.01)
        assert blocks, "no window-0 block reached the reader"
        assert not finished.is_set(), (
            "reader finished before the straggler map was written"
        )
        t_window0 = blocks[0][0]
        t_straggler = time.monotonic()
        assert t_window0 < t_straggler

        w = executors[3 % E].get_writer(handle, 3)
        w.write(records_per_map[3])
        w.stop(True)
        th.join(timeout=60)
        assert finished.is_set(), "reader never completed"

        # every partition-0 record arrived exactly once
        deser = executors[0].serializer.deserialize
        got = [kv for _t, b in blocks for kv in deser(b)]
        expect = [
            kv for recs in records_per_map for kv in recs
            if part.partition(kv[0]) == 0
        ]
        assert sorted(map(repr, got)) == sorted(map(repr, expect))
        # pump saw both windows
        evs = executors[0].windowed_plane.window_events(66)
        assert [w for w, _t, _b in evs] == [0, 1], evs
    finally:
        for m in executors + [driver]:
            m.stop()


def test_windowed_plane_all_partitions_via_get_reader(devices):
    """Every partition read through reducer-issued get_reader calls
    (one per partition, pid % E ownership) over 3 plan windows."""
    net, conf, driver, executors = _windowed_plane_cluster(2, 46400)
    try:
        E = len(executors)
        num_maps, num_parts = 6, 8
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(67, num_maps, part)
        records_per_map = [
            [(f"m{m}k{j}", j) for j in range(40)] for m in range(num_maps)
        ]
        for m, recs in enumerate(records_per_map):
            w = executors[m % E].get_writer(handle, m)
            w.write(recs)
            w.stop(True)
        results = {}
        errors = {}

        def reduce_task(pid):
            try:
                r = executors[pid % E].get_reader(handle, pid, pid + 1, {})
                results[pid] = list(r.read())
            except BaseException as e:
                errors[pid] = e

        threads = [
            threading.Thread(target=reduce_task, args=(p,), daemon=True)
            for p in range(num_parts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        for pid, recs in results.items():
            for k, _v in recs:
                assert part.partition(k) == pid
        got = [kv for recs in results.values() for kv in recs]
        expect = [kv for recs in records_per_map for kv in recs]
        assert sorted(map(repr, got)) == sorted(map(repr, expect))
        for e in executors:
            evs = e.windowed_plane.window_events(67)
            assert [w for w, _t, _b in evs] == [0, 1, 2], evs
    finally:
        for m in executors + [driver]:
            m.stop()


def test_windowed_plane_ownership_violation_fails_fast(devices):
    """Asking a windowed reader for a partition another host owns is a
    loud FetchFailedError, not silent emptiness."""
    from sparkrdma_tpu.shuffle.reader import FetchFailedError

    net, conf, driver, executors = _windowed_plane_cluster(0, 46600)
    try:
        E = len(executors)
        part = HashPartitioner(4)
        handle = driver.register_shuffle(68, 2, part)
        for m in range(2):
            w = executors[m % E].get_writer(handle, m)
            w.write([(f"k{j}", j) for j in range(10)])
            w.stop(True)
        for e in executors:
            e.windowed_plane.join(68)
        # partition 1 belongs to executor 1; executor 0 must refuse
        r = executors[0].get_reader(handle, 1, 2, {})
        with pytest.raises(FetchFailedError, match="belongs to host"):
            list(r.read())
    finally:
        for m in executors + [driver]:
            m.stop()


def test_windowed_plane_context_e2e(devices):
    """Job-layer round trip on the unified plane: reduce_by_key and
    sort_by_key through TpuShuffleContext with readPlane=windowed."""
    import numpy as np

    from sparkrdma_tpu.api import TpuShuffleContext

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.readPlane": "windowed",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
    })
    with TpuShuffleContext(
        num_executors=2, conf=conf, base_port=46800
    ) as ctx:
        keys = np.arange(3000, dtype=np.int64) % 17
        vals = np.arange(3000, dtype=np.int64)
        got = dict(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .reduce_by_key("sum")
            .collect()
        )
        expect = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            expect[k] = expect.get(k, 0) + v
        assert got == expect
        srt = (
            ctx.parallelize_columns(keys[:500], vals[:500], num_slices=4)
            .sort_by_key()
            .collect()
        )
        assert [k for k, _v in srt] == sorted(keys[:500].tolist())


def test_windowed_failure_then_stage_retry_completes(devices):
    """The lineage-retry contract the fail-fast design leans on
    (VERDICT r3 item 7; reference: fetch failure → stage retry,
    RdmaShuffleFetcherIterator.scala:368-373): kill an executor
    mid-windowed-shuffle, every reader fails FAST (not at the 30s
    location timer), then the job layer re-registers the shuffle on the
    survivors and completes it."""
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane
    from sparkrdma_tpu.shuffle.reader import FetchFailedError

    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 46950,
        "spark.shuffle.tpu.heartbeatInterval": "100ms",
        # the victim is detected by its probe SEND failing (instant),
        # not by ack staleness — keep the ack timeout GIL-tolerant so
        # collective-phase contention can't spuriously prune survivors
        "spark.shuffle.tpu.heartbeatTimeout": "3s",
        # survivors must fail via DETECTION fan-out (sub-second), not
        # this timer; it only bounds the partitioned victim's own wait
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "8s",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
        "spark.shuffle.tpu.readPlane": "windowed",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=47050 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(3)
    ]
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == 3 for e in executors):
                break
            time.sleep(0.01)
        E = 3
        session = BulkShuffleSession(
            TileExchange(make_mesh(E), tile_bytes=1 << 12), E,
            timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
        )
        for e in executors:
            e.windowed_plane = WindowedReadPlane(e, session=session)

        num_maps, num_parts = 6, 6
        part = HashPartitioner(num_parts)
        records_per_map = [
            [(f"m{m}k{j}", (m, j)) for j in range(30)]
            for m in range(num_maps)
        ]
        handle = driver.register_shuffle(75, num_maps, part)
        for m in range(3):  # window 0 (2 maps) plannable; map 3+ missing
            w = executors[m % E].get_writer(handle, m)
            w.write(records_per_map[m])
            w.stop(True)

        results = {}
        errors = {}
        error_times = {}

        def reduce_task(pid, execs, hdl, nE):
            try:
                r = execs[pid % nE].get_reader(hdl, pid, pid + 1, {})
                results[pid] = list(r.read())
            except BaseException as e:
                errors[pid] = e
                error_times[pid] = time.monotonic()

        threads = [
            threading.Thread(
                target=reduce_task, args=(p, executors, handle, E),
                daemon=True,
            )
            for p in range(num_parts)
        ]
        for t in threads:
            t.start()
        # window 0 lands on every host...
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(
                e.windowed_plane.window_events(75) for e in executors
            ):
                break
            time.sleep(0.01)
        assert all(
            e.windowed_plane.window_events(75) for e in executors
        ), "window 0 never exchanged"
        # ...then the victim dies before the remaining maps fill
        victim = executors[2]
        t_kill = time.monotonic()
        net.partition(victim.node.address)
        for t in threads:
            t.join(timeout=45)
        assert not results, f"readers completed despite the loss: {results}"
        assert set(errors) == set(range(num_parts)), errors
        assert all(
            isinstance(e, FetchFailedError) for e in errors.values()
        ), errors
        # SURVIVOR reducers fail via the driver's fan-out in seconds;
        # the victim's own reducers may ride to the location timer (the
        # doom reply cannot reach a partitioned host — in a real
        # deployment they die with the process)
        for pid, t_err in error_times.items():
            if pid % E != 2:
                assert t_err - t_kill < 5, (
                    f"survivor partition {pid} took "
                    f"{t_err - t_kill:.1f}s — fan-out not fast"
                )

        # -- the stage retry: same data, new shuffle id, survivors only
        survivors = executors[:2]
        handle2 = driver.register_shuffle(76, num_maps, part)
        for m in range(num_maps):
            w = survivors[m % 2].get_writer(handle2, m)
            w.write(records_per_map[m])
            w.stop(True)
        session2 = BulkShuffleSession(
            TileExchange(make_mesh(2), tile_bytes=1 << 12), 2,
            timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
        )
        for e in survivors:
            e.windowed_plane = WindowedReadPlane(e, session=session2)
        results.clear()
        errors.clear()
        threads = [
            threading.Thread(
                target=reduce_task, args=(p, survivors, handle2, 2),
                daemon=True,
            )
            for p in range(num_parts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, f"retry failed: {errors}"
        got = [kv for recs in results.values() for kv in recs]
        expect = [kv for recs in records_per_map for kv in recs]
        assert sorted(map(repr, got)) == sorted(map(repr, expect))
        # 6 maps / window of 2 → 3 retry windows on each survivor
        for e in survivors:
            evs = [w for w, _t, _b in e.windowed_plane.window_events(76)]
            assert evs == [0, 1, 2], evs
    finally:
        net.heal(executors[2].node.address)
        for m in executors + [driver]:
            m.stop()


def _run_concurrent_jobs(jobs, timeout=120):
    """Run callables concurrently; returns {tag: result}.  Fails loudly
    on a hung job (join timeout) or any job error."""
    out = {}
    errs = {}

    def wrap(tag, fn):
        try:
            out[tag] = fn()
        except BaseException as e:
            errs[tag] = e

    ts = [
        threading.Thread(target=wrap, args=(tag, fn), daemon=True)
        for tag, fn in jobs
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in ts), "hung job"
    assert not errs, errs
    return out


def test_windowed_plane_concurrent_shuffles_one_session(devices):
    """Two shuffles running CONCURRENTLY through one context must not
    cross-contribute rows into the shared session barrier (rounds are
    keyed by (shuffle_id, window))."""
    import numpy as np

    from sparkrdma_tpu.api import TpuShuffleContext

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.readPlane": "windowed",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
    })
    with TpuShuffleContext(
        num_executors=2, conf=conf, base_port=48300
    ) as ctx:
        keys_a = np.arange(4000, dtype=np.int64) % 7
        vals_a = np.arange(4000, dtype=np.int64)
        keys_b = np.arange(4000, dtype=np.int64) % 7  # same shapes →
        vals_b = np.arange(4000, dtype=np.int64) * 10  # same lengths

        def job(keys, vals):
            return lambda: dict(
                ctx.parallelize_columns(keys, vals, num_slices=4)
                .reduce_by_key("sum", num_partitions=4)
                .collect()
            )

        out = _run_concurrent_jobs(
            [("a", job(keys_a, vals_a)), ("b", job(keys_b, vals_b))]
        )
        for tag, vals in (("a", vals_a), ("b", vals_b)):
            keys = keys_a
            expect = {}
            for k, v in zip(keys.tolist(), vals.tolist()):
                expect[k] = expect.get(k, 0) + v
            assert out[tag] == expect, f"shuffle {tag} corrupted"


def test_windowed_plane_over_spilled_file_backed_commits(devices, tmp_path):
    """Composition: the unified plane's window collectives source their
    streams from SPILLED, file-backed map outputs (per-partition
    O_DIRECT spill files promoted to shuffle files) — the GB-scale disk
    path and the device plane working as one system."""
    import numpy as np

    from sparkrdma_tpu.api import TpuShuffleContext

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.readPlane": "windowed",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
        "spark.shuffle.tpu.shuffleSpillRecordThreshold": "1000",
        "spark.shuffle.tpu.spillDir": str(tmp_path),
    })
    with TpuShuffleContext(
        num_executors=2, conf=conf, base_port=48500
    ) as ctx:
        keys = np.arange(40000, dtype=np.int64) % 29
        vals = np.arange(40000, dtype=np.int64)
        got = dict(
            ctx.parallelize_columns(keys, vals, num_slices=6)
            .reduce_by_key("sum", num_partitions=6)
            .collect()
        )
        # the exchange really ran collective rounds over spilled bytes
        stats = ctx.executors[0].windowed_plane.stats()
        assert stats["payload_bytes_moved"] > 0
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert got == expect
    import glob

    assert not glob.glob(str(tmp_path / "sparkrdma*")), "files leaked"


def test_windowed_plane_many_concurrent_shuffles_no_leak(devices):
    """4 shuffles in flight through one context: every job exact, and
    the shared session's keyed-round table drains to empty (each round
    pops once all participants are served)."""
    import numpy as np

    from sparkrdma_tpu.api import TpuShuffleContext

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.readPlane": "windowed",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
    })
    with TpuShuffleContext(
        num_executors=2, conf=conf, base_port=48900
    ) as ctx:
        def job(tag):
            def run():
                keys = np.arange(3000, dtype=np.int64) % (5 + tag)
                vals = np.full(3000, tag + 1, np.int64)
                return dict(
                    ctx.parallelize_columns(keys, vals, num_slices=4)
                    .reduce_by_key("sum", num_partitions=4)
                    .collect()
                )
            return run

        out = _run_concurrent_jobs([(t, job(t)) for t in range(4)])
        for tag in range(4):
            nk = 5 + tag
            expect = {
                k: (tag + 1) * len(
                    [x for x in range(3000) if x % nk == k]
                )
                for k in range(nk)
            }
            assert out[tag] == expect, f"job {tag} corrupted"
        session = ctx.executors[0].windowed_plane._bulk.session
        with session._cv:
            assert not session._keyed, (
                f"keyed rounds leaked: {list(session._keyed)}"
            )


def test_windowed_chaos_random_loss(devices):
    """Seeded chaos over the windowed plane: an executor loss at a
    RANDOM point in the map/window schedule must leave every reducer
    in one of two states — exact results for its partition, or a
    prompt FetchFailedError — never wrong data or a hang.  The
    deterministic kill-and-retry scenario is covered above; this sweep
    varies WHERE the loss lands relative to the window plans."""
    import os
    import random

    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane
    from sparkrdma_tpu.shuffle.reader import FetchFailedError

    rng = random.Random(int(os.environ.get(
        "SPARKRDMA_TEST_CHAOS_SEED", "4321"
    )))
    E, num_maps, num_parts = 3, 6, 6
    n_trials = int(os.environ.get("SPARKRDMA_TEST_CHAOS_TRIALS", "2"))
    for trial in range(n_trials):
        net = LoopbackNetwork()
        conf = TpuShuffleConf({
            "spark.shuffle.tpu.driverPort": 46200,
            "spark.shuffle.tpu.heartbeatInterval": "100ms",
            "spark.shuffle.tpu.heartbeatTimeout": "3s",
            "spark.shuffle.tpu.partitionLocationFetchTimeout": "8s",
            "spark.shuffle.tpu.bulkWindowMaps": "2",
            "spark.shuffle.tpu.readPlane": "windowed",
        })
        driver = TpuShuffleManager(conf, is_driver=True, network=net)
        executors = [
            TpuShuffleManager(
                conf, is_driver=False, network=net,
                port=46300 + i * 10, executor_id=str(i),
                stage_to_device=False,
            )
            for i in range(E)
        ]
        victim = executors[rng.randrange(1, E)]
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if all(len(e._peers) == E for e in executors):
                    break
                time.sleep(0.01)
            session = BulkShuffleSession(
                TileExchange(make_mesh(E), tile_bytes=1 << 12), E,
                timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
            )
            for e in executors:
                e.windowed_plane = WindowedReadPlane(e, session=session)
            part = HashPartitioner(num_parts)
            records_per_map = [
                [(f"m{m}k{j}", (m, j)) for j in range(25)]
                for m in range(num_maps)
            ]
            sid = 800 + trial
            handle = driver.register_shuffle(sid, num_maps, part)

            fault = rng.choice(["none", "loss", "loss"])
            kill_after = rng.randrange(2, num_maps + 1)

            results, errors = {}, {}

            def reduce_task(pid):
                try:
                    r = executors[pid % E].get_reader(
                        handle, pid, pid + 1, {}
                    )
                    results[pid] = list(r.read())
                except BaseException as e:
                    errors[pid] = e

            threads = [
                threading.Thread(target=reduce_task, args=(p,),
                                 daemon=True)
                for p in range(num_parts)
            ]
            for t in threads:
                t.start()
            for m in range(num_maps):
                if fault == "loss" and m == kill_after:
                    net.partition(victim.node.address)
                w = executors[m % E].get_writer(handle, m)
                w.write(records_per_map[m])
                try:
                    w.stop(True)
                except BaseException:
                    # the victim's own publish may fail mid-kill;
                    # readers then fail fast — acceptable
                    pass
                time.sleep(rng.uniform(0, 0.01))
            if fault == "loss" and kill_after == num_maps:
                net.partition(victim.node.address)
            # generous join: a loss trial legitimately rides the
            # location timer + barrier timeout, and a loaded box (the
            # seed soaks run several of these concurrently) stretches
            # that chain well past its nominal length
            for t in threads:
                t.join(timeout=120)
            hung = [p for p in range(num_parts)
                    if p not in results and p not in errors]
            assert not hung, f"trial {trial}: readers hung: {hung}"
            # completed partitions must be EXACT regardless of timing
            all_records = [kv for recs in records_per_map for kv in recs]
            for pid, got in results.items():
                want = [(k, v) for k, v in all_records
                        if part.partition(k) == pid]
                assert sorted(map(repr, got)) == sorted(map(repr, want)), (
                    f"trial {trial} partition {pid} inexact"
                )
            for pid, err in errors.items():
                assert isinstance(err, FetchFailedError), (
                    f"trial {trial} partition {pid}: {err!r}"
                )
            if fault == "none":
                assert not errors, f"trial {trial}: {errors}"
        finally:
            net.heal(victim.node.address)
            for m in executors + [driver]:
                m.stop()


def test_windowed_generator_close_cancels_prefetched_waiter():
    """ADVICE round-5 fix: abandoning _iter_windowed_exchanges after a
    yield (GeneratorExit) must cancel the PREFETCHED next-window plan
    waiter instead of leaking its registered callback (and count the
    cancellation when the registry is enabled)."""
    from types import SimpleNamespace

    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.shuffle.bulk import BulkExchangeReader

    cancelled = []

    class Waiter:
        def __init__(self, window):
            self.window = window

        def wait(self):
            return SimpleNamespace(final=False, window=self.window)

        def cancel(self):
            cancelled.append(self.window)

    reader = BulkExchangeReader.__new__(BulkExchangeReader)
    reader._fetch_plan_async = lambda sid, window: Waiter(window)
    reader._exchange_rows = (
        lambda sid, window, plan: (plan, 2, [b"", b""])
    )

    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    try:
        gen = reader._iter_windowed_exchanges(0)
        plan, _e, _row = next(gen)
        assert plan.window == 0
        # window 1's waiter is in flight; abandoning the generator
        # here must cancel it
        gen.close()
        assert cancelled == [1]
        snap = GLOBAL_REGISTRY.snapshot()
        vals = {c["name"]: c["value"] for c in snap["counters"]}
        assert vals.get(
            "shuffle_plan_waiters_cancelled_total") == 1
    finally:
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()


def test_windowed_generator_wait_failure_cancels_inflight_waiter():
    """An error inside the plan wait must also cancel whatever waiter
    is still in flight before the generator frame unwinds."""
    from sparkrdma_tpu.shuffle.bulk import BulkExchangeReader

    cancelled = []

    class FailingWaiter:
        def __init__(self, window):
            self.window = window

        def wait(self):
            raise RuntimeError("driver gone")

        def cancel(self):
            cancelled.append(self.window)

    reader = BulkExchangeReader.__new__(BulkExchangeReader)
    reader._fetch_plan_async = (
        lambda sid, window: FailingWaiter(window)
    )
    gen = reader._iter_windowed_exchanges(0)
    try:
        next(gen)
        raise AssertionError("expected RuntimeError")
    except RuntimeError:
        pass
    assert cancelled == [0]
