"""Async completion-driven transport core (transport/dispatcher.py):
bit-exactness sweeps async vs threaded vs loopback, async↔threaded
wire interop in both directions, serve-credit bounding and write
backpressure under the event loop, dispatcher lifecycle/census, and
the striped-reads × serve-credits × decode-pipeline end-to-end A/B."""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.arena import ArenaManager
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.transport.channel import ChannelType, FnCompletionListener
from sparkrdma_tpu.transport.node import Node, transport_census
from sparkrdma_tpu.utils.types import BlockLocation

BASE_PORT = 27500

_PATTERN = (np.arange(6 << 20, dtype=np.uint32) % 251).astype(np.uint8)


def _conf(async_mode, extra=None):
    d = {
        "spark.shuffle.tpu.transportAsyncDispatcher": async_mode,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "128k",
    }
    d.update(extra or {})
    return TpuShuffleConf(d)


def _pair(port, conf_a, conf_b=None):
    """Two TCP nodes with per-node confs (mixed-mode interop needs
    the requester and responder on different engines)."""
    net = TcpNetwork()
    a = Node(("127.0.0.1", port), conf_a)
    b = Node(("127.0.0.1", port + 7), conf_b or conf_a)
    net.register(a)
    net.register(b)
    arena = ArenaManager()
    seg = arena.register(_PATTERN, zero_copy_ok=True)
    b.register_block_store(seg.mkey, arena)
    return net, a, b, seg.mkey


def _teardown(net, a, b):
    a.stop()
    b.stop()
    net.unregister(a)
    net.unregister(b)


def _group_read(group, locs, timeout=30, on_progress=None):
    done = threading.Event()
    res = {}
    group.read_blocks(
        locs,
        FnCompletionListener(
            lambda blocks: (res.setdefault("blocks", blocks), done.set()),
            lambda e: (res.setdefault("error", e), done.set()),
        ),
        on_progress=on_progress,
    )
    assert done.wait(timeout), "group read hung"
    if "error" in res:
        raise res["error"]
    return res["blocks"]


def _as_np(blk):
    if isinstance(blk, np.ndarray):
        return blk
    return np.frombuffer(memoryview(blk), np.uint8)


def _rpc_echo(a, b, net, payload=b"ping-frame", timeout=10):
    """One echo round-trip a→b→a; returns the echoed frame."""
    got = {}
    pong = threading.Event()

    def echo(channel, frame):
        channel.reply_channel().send_rpc([frame], FnCompletionListener())

    def on_pong(_channel, frame):
        got["frame"] = frame
        pong.set()

    b.set_receive_listener(echo)
    a.set_receive_listener(on_pong)
    ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, net.connect)
    ch.send_rpc([payload], FnCompletionListener())
    assert pong.wait(timeout), "rpc echo hung"
    return got["frame"]


_LOCS_SPEC = [
    (3, 100),            # tiny (small-read lane)
    (103, 128 << 10),    # == threshold: NOT striped
    (5, (128 << 10) + 1),  # barely striped
    (1 << 20, 3 << 20),  # bulk striped
    (0, 1),
]


def _read_locs(mkey):
    return [BlockLocation(a, n, mkey) for a, n in _LOCS_SPEC]


# -- bit-exactness: async vs threaded vs loopback -----------------------------


def test_async_vs_threaded_vs_loopback_bit_exact():
    """The same mixed small/striped location batch serves bit-identical
    payloads on the async dispatcher, the thread-per-lane path, and
    loopback."""
    results = {}
    for name, mode, port in [
        ("async", "on", BASE_PORT),
        ("threaded", "off", BASE_PORT + 20),
    ]:
        net, a, b, mkey = _pair(port, _conf(mode))
        try:
            blocks = _group_read(
                a.get_read_group(b.address, net.connect), _read_locs(mkey)
            )
            results[name] = [bytes(memoryview(_as_np(x))) for x in blocks]
        finally:
            _teardown(net, a, b)
    lnet = LoopbackNetwork()
    la = Node(("127.0.0.1", BASE_PORT + 40), _conf("on"))
    lb = Node(("127.0.0.1", BASE_PORT + 47), _conf("on"))
    lnet.register(la)
    lnet.register(lb)
    arena = ArenaManager()
    seg = arena.register(_PATTERN, zero_copy_ok=True)
    lb.register_block_store(seg.mkey, arena)
    try:
        blocks = _group_read(
            la.get_read_group(lb.address, lnet.connect),
            _read_locs(seg.mkey),
        )
        results["loopback"] = [
            bytes(memoryview(_as_np(x))) for x in blocks
        ]
    finally:
        _teardown(lnet, la, lb)
    assert results["async"] == results["threaded"] == results["loopback"]
    for (addr, n), payload in zip(_LOCS_SPEC, results["async"]):
        assert payload == _PATTERN[addr:addr + n].tobytes()


@pytest.mark.parametrize("client_mode,server_mode,port", [
    ("on", "off", BASE_PORT + 60),   # async client ↔ threaded server
    ("off", "on", BASE_PORT + 80),   # threaded client ↔ async server
])
def test_wire_interop_mixed_modes(client_mode, server_mode, port):
    """The two engines speak the same wire format: striped reads AND
    RPC echo complete exactly across a mixed-mode pair, in both
    directions."""
    net, a, b, mkey = _pair(
        port, _conf(client_mode), _conf(server_mode)
    )
    try:
        blocks = _group_read(
            a.get_read_group(b.address, net.connect), _read_locs(mkey)
        )
        for (addr, n), blk in zip(_LOCS_SPEC, blocks):
            assert bytes(memoryview(_as_np(blk))) == \
                _PATTERN[addr:addr + n].tobytes()
        assert _rpc_echo(a, b, net) == b"ping-frame"
    finally:
        _teardown(net, a, b)


# -- serve credits and write backpressure on the loop -------------------------


def test_async_serve_credit_bounding_completes_without_deadlock():
    """Serve credits far below one response: every serve clamps, runs
    alone, and releases on SEND COMPLETION (deferred release) — many
    concurrent bulk reads all complete exactly, no deadlock, no hang."""
    conf = _conf("on", {
        "spark.shuffle.tpu.transportServeCreditBytes": "1m",
        "spark.shuffle.tpu.transportServeThreads": 2,
    })
    net, a, b, mkey = _pair(BASE_PORT + 100, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        done = threading.Event()
        res = {"ok": 0, "err": None}
        lock = threading.Lock()
        n_reads = 6

        def one(i):
            def ok(blocks):
                with lock:
                    res["ok"] += 1
                    for blk in blocks:
                        if not np.array_equal(
                            _as_np(blk), _PATTERN[0:3 << 20]
                        ):
                            res["err"] = AssertionError("corrupt")
                    if res["ok"] == n_reads:
                        done.set()

            def bad(e):
                res["err"] = e
                done.set()

            group.read_blocks(
                [BlockLocation(0, 3 << 20, mkey)],
                FnCompletionListener(ok, bad),
            )

        for i in range(n_reads):
            one(i)
        assert done.wait(60), "credit-bounded reads hung"
        assert res["err"] is None, res["err"]
        assert res["ok"] == n_reads
    finally:
        _teardown(net, a, b)


def test_async_write_backpressure_tiny_backlog_still_exact():
    """A send-backlog high-water far below one response forces the
    responder's pause/resume read-interest machinery through many
    cycles — transfers stay bit-exact and nothing hangs."""
    conf = _conf("on", {
        "spark.shuffle.tpu.transportSendBacklogBytes": "64k",
    })
    net, a, b, mkey = _pair(BASE_PORT + 120, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        for _ in range(3):
            blocks = _group_read(
                group, [BlockLocation(1 << 20, 4 << 20, mkey)]
            )
            assert np.array_equal(
                _as_np(blocks[0]), _PATTERN[1 << 20: 5 << 20]
            )
    finally:
        _teardown(net, a, b)


# -- lifecycle / failure ------------------------------------------------------


def test_async_dead_peer_fails_fast_and_dispatcher_stays_healthy():
    """Killing the responder node fails in-flight reads promptly with
    a clean error; the surviving node's dispatcher keeps serving a
    fresh peer afterwards."""
    conf = _conf("on")
    net, a, b, mkey = _pair(BASE_PORT + 140, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        blocks = _group_read(group, [BlockLocation(0, 2 << 20, mkey)])
        assert _as_np(blocks[0]).shape[0] == 2 << 20
        failed = threading.Event()
        res = {}
        group.read_blocks(
            [BlockLocation(0, 4 << 20, mkey)],
            FnCompletionListener(
                lambda blks: (res.setdefault("blocks", blks),
                              failed.set()),
                lambda e: (res.setdefault("error", e), failed.set()),
            ),
        )
        b.stop()
        net.unregister(b)
        assert failed.wait(30), "read against dead peer hung"
        # either the bytes raced home whole, or it failed cleanly
        if "blocks" in res:
            assert _as_np(res["blocks"][0]).shape[0] == 4 << 20
        # the dispatcher serves a FRESH responder immediately
        c = Node(("127.0.0.1", BASE_PORT + 155), conf)
        net.register(c)
        arena = ArenaManager()
        seg = arena.register(_PATTERN, zero_copy_ok=True)
        c.register_block_store(seg.mkey, arena)
        try:
            group_c = a.get_read_group(c.address, net.connect)
            blocks = _group_read(
                group_c, [BlockLocation(7, 1 << 20, seg.mkey)]
            )
            assert np.array_equal(
                _as_np(blocks[0]), _PATTERN[7:7 + (1 << 20)]
            )
        finally:
            c.stop()
            net.unregister(c)
    finally:
        a.stop()
        net.unregister(a)


def test_async_node_runs_one_event_loop_thread():
    """A node serving N peers × S stripes runs its transport on ONE
    event-loop thread: no per-channel readers, no accept thread."""
    # earlier threaded-mode tests in this process may still be
    # draining their reader threads — wait them out for a clean floor
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        before = transport_census()
        if before["by_role"].get("tcp", 0) == 0:
            break
        time.sleep(0.05)
    tcp_floor = before["by_role"].get("tcp", 0)
    conf = _conf("on", {
        "spark.shuffle.tpu.transportNumStripes": 4,
    })
    net, a, b, mkey = _pair(BASE_PORT + 160, conf)
    try:
        group = a.get_read_group(b.address, net.connect)
        _group_read(group, _read_locs(mkey))  # connects 1 + 4 lanes
        census = transport_census()
        assert census["by_role"].get("disp", 0) == 2  # one per node
        # 1 small lane + 4 data lanes × 2 endpoints = 10 sockets, yet
        # ZERO new reader/accept threads
        assert census["by_role"].get("tcp", 0) == tcp_floor, census
    finally:
        _teardown(net, a, b)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        after = transport_census()
        if after["by_role"].get("disp", 0) == before["by_role"].get(
                "disp", 0):
            break
        time.sleep(0.05)
    assert after["by_role"].get("disp", 0) == before["by_role"].get(
        "disp", 0), (before, after)


# -- end-to-end: striped reads × serve credits × decode pipeline --------------


def _shuffle_roundtrip(port, async_mode, decode_threads):
    """Write one striped-sized shuffle over TCP and read it back with
    the decode pipeline; returns the sorted (key, value-bytes) list."""
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner

    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": port,
        "spark.shuffle.tpu.transportAsyncDispatcher": async_mode,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        "spark.shuffle.tpu.transportServeCreditBytes": "2m",
        "spark.shuffle.tpu.decodeThreads": decode_threads,
        "spark.shuffle.tpu.compress": True,
        "spark.shuffle.tpu.shuffleReadBlockSize": "1m",
        "spark.shuffle.tpu.maxBytesInFlight": "4m",
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "30s",
    })
    driver = TpuShuffleManager(
        conf, is_driver=True, network=TcpNetwork(), port=port,
        stage_to_device=False,
    )
    ex = TpuShuffleManager(
        conf, is_driver=False, network=TcpNetwork(), port=port + 11,
        executor_id="x", stage_to_device=False,
    )
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(ex._peers) < 1:
        time.sleep(0.01)
    try:
        handle = driver.register_shuffle(
            31, 1, HashPartitioner(2), key_ordering=True
        )
        rows = [
            (f"k{j:05d}", bytes([j % 251]) * 4096) for j in range(700)
        ]
        w = ex.get_writer(handle, 0)
        w.write(rows)
        w.stop(True)
        out = []
        for pid in range(2):
            reader = driver.get_reader(
                handle, pid, pid + 1, {ex.local_smid: [0]}
            )
            out.extend(
                (k, bytes(memoryview(v))) for k, v in reader.read()
            )
        return sorted(out)
    finally:
        ex.stop()
        driver.stop()


@pytest.mark.parametrize("decode_threads", [0, 2])
def test_e2e_shuffle_async_vs_threaded_bit_exact(decode_threads):
    """Striped fetches × bounded serve credits × the decode pipeline,
    end to end over real sockets: the async transport core returns the
    exact record stream of the threaded one."""
    got_async = _shuffle_roundtrip(
        BASE_PORT + 200 + decode_threads * 40, "on", decode_threads
    )
    got_threaded = _shuffle_roundtrip(
        BASE_PORT + 220 + decode_threads * 40, "off", decode_threads
    )
    assert got_async == got_threaded
    assert len(got_async) == 700
