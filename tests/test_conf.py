"""Config parsing, clamping, defaults (SURVEY.md §2, RdmaShuffleConf)."""

from sparkrdma_tpu.conf import TpuShuffleConf, parse_byte_size, parse_time_ms


def test_byte_size_parsing():
    assert parse_byte_size("8m") == 8 << 20
    assert parse_byte_size("256k") == 256 << 10
    assert parse_byte_size("10g") == 10 << 30
    assert parse_byte_size("4096") == 4096
    assert parse_byte_size(4096) == 4096
    assert parse_byte_size("1.5k") == 1536


def test_time_parsing():
    assert parse_time_ms("20s") == 20000
    assert parse_time_ms("50ms") == 50
    assert parse_time_ms(2) == 2000


def test_defaults():
    c = TpuShuffleConf()
    assert c.recv_queue_depth == 1024
    assert c.send_queue_depth == 4096
    assert c.recv_wr_size == 4096
    assert c.sw_flow_control is True
    assert c.max_buffer_allocation_size == 10 << 30
    assert c.shuffle_write_block_size == 8 << 20
    assert c.shuffle_read_block_size == 256 << 10
    assert c.max_bytes_in_flight == 1 << 20
    assert c.max_agg_block == 2 << 20
    assert c.max_agg_prealloc == 0
    assert c.collect_shuffle_reader_stats is False
    assert c.partition_location_fetch_timeout_ms == 120_000
    assert c.connect_retries == 5
    assert c.connect_backoff_ms == 50
    assert c.fetch_retry_count == 3
    assert c.fetch_retry_wait_ms == 50
    assert c.fetch_retry_max_ms == 10_000
    assert c.fault_inject == ""


def test_clamping_and_fallback():
    c = TpuShuffleConf({
        "spark.shuffle.tpu.recvQueueDepth": "64",        # below min 256 → clamp
        "spark.shuffle.tpu.sendQueueDepth": "garbage",   # unparsable → default
        "spark.shuffle.tpu.shuffleReadBlockSize": "1k",  # below min 16k → clamp
    })
    assert c.recv_queue_depth == 256
    assert c.send_queue_depth == 4096
    assert c.shuffle_read_block_size == 16 << 10


def test_connect_retry_conf_fallbacks():
    # new name wins; the old one still works (two spellings: the tpu
    # key feeds the default chain, the rdma key rides LEGACY_RENAMES)
    c = TpuShuffleConf({"spark.shuffle.tpu.connectRetries": "9"})
    assert c.connect_retries == 9
    c = TpuShuffleConf({"spark.shuffle.tpu.maxConnectionAttempts": "7"})
    assert c.connect_retries == 7
    c = TpuShuffleConf({"spark.shuffle.rdma.maxConnectionAttempts": "4"})
    assert c.connect_retries == 4
    c = TpuShuffleConf({
        "spark.shuffle.tpu.connectRetries": "9",
        "spark.shuffle.tpu.maxConnectionAttempts": "7",
    })
    assert c.connect_retries == 9


def test_set_and_get():
    c = TpuShuffleConf()
    c.set("maxBytesInFlight", "4m")
    assert c.max_bytes_in_flight == 4 << 20
    c.set_driver_port(12345)
    assert c.driver_port == 12345


def test_device_list_parsing():
    c = TpuShuffleConf({"spark.shuffle.tpu.deviceList": "0-2,5"})
    assert c.parse_device_list(8) == [0, 1, 2, 5]
    # out-of-range entries dropped; empty result → all
    assert c.parse_device_list(2) == [0, 1]
    assert TpuShuffleConf().parse_device_list(4) == [0, 1, 2, 3]
    bad = TpuShuffleConf({"spark.shuffle.tpu.deviceList": "x-y"})
    assert bad.parse_device_list(3) == [0, 1, 2]


def test_tracer_bounded_events():
    from sparkrdma_tpu.utils.trace import Tracer

    t = Tracer(enabled=True, max_events=10)
    for i in range(25):
        t.instant("e", i=i)
    assert len(t.events) == 10
    assert t.dropped == 15


def test_compressed_serializer_roundtrip():
    from sparkrdma_tpu.utils.serde import CompressedSerializer, PickleSerializer

    recs = [(i, "value-%d" % i) for i in range(5000)]
    for codec in ("zlib", "lzma"):
        s = CompressedSerializer(PickleSerializer(), codec=codec)
        data = s.serialize(recs)
        assert list(s.deserialize(data)) == recs
        # compressible payload actually shrinks
        assert len(data) < len(PickleSerializer().serialize(recs))
    # tiny payloads stored raw (tag 0)
    s = CompressedSerializer(min_size=1 << 20)
    data = s.serialize([(1, 2)])
    assert data[0] == 0
    assert list(s.deserialize(data)) == [(1, 2)]


def test_manager_compress_conf_picks_codec():
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.utils.serde import CompressedSerializer

    conf = TpuShuffleConf({"spark.shuffle.tpu.compress": "true"})
    assert conf.compress and conf.compress_codec == "zlib"


def test_legacy_rdma_namespace_aliases():
    """A reference user's spark.shuffle.rdma.* settings apply unchanged
    (RdmaShuffleConf.scala:34-126); explicit tpu keys win; useOdp maps
    to its on-demand-staging analog."""
    from sparkrdma_tpu.conf import TpuShuffleConf

    conf = TpuShuffleConf({
        "spark.shuffle.rdma.shuffleReadBlockSize": "512k",
        "spark.shuffle.rdma.maxBytesInFlight": "2m",
        "spark.shuffle.rdma.useOdp": "true",
        "spark.shuffle.rdma.driverPort": 31999,
        # explicit tpu key beats its legacy alias
        "spark.shuffle.rdma.maxAggBlock": "1m",
        "spark.shuffle.tpu.maxAggBlock": "4m",
    })
    assert conf.shuffle_read_block_size == 512 << 10
    assert conf.max_bytes_in_flight == 2 << 20
    assert conf.lazy_staging is True
    assert conf.driver_port == 31999
    assert conf.max_agg_block == 4 << 20


def test_core_census_resolution(monkeypatch):
    """coreCensus override > dispatcherCpuList pin > affinity mask;
    the cpu_count-keyed defaults (decodeThreads, bulkPipelineWindows,
    transportPollSpinUs, tierPrefetch) all follow the census, so a
    CPU-pinned containerized executor gets single-core-correct
    defaults even on a many-core machine."""
    import sparkrdma_tpu.conf as conf_mod

    # pretend a 16-core machine whose cgroup allows this process 16
    monkeypatch.setattr(conf_mod.os, "cpu_count", lambda: 16)
    monkeypatch.setattr(conf_mod, "host_core_census", lambda: 16)

    wide = TpuShuffleConf()
    assert wide.core_census == 16
    assert wide.decode_threads == 4
    assert wide.transport_poll_spin_us == 40
    assert wide.tier_prefetch is True
    assert wide.bulk_pipeline_windows is True

    # a 1-CPU dispatcher pin shrinks every derived default to the
    # single-core fallbacks, machine count notwithstanding
    pinned = TpuShuffleConf({"spark.shuffle.tpu.dispatcherCpuList": "3"})
    assert pinned.core_census == 1
    assert pinned.decode_threads == 0
    assert pinned.transport_poll_spin_us == 0
    assert pinned.tier_prefetch is False
    assert pinned.bulk_pipeline_windows is False

    # explicit coreCensus beats both the pin and the mask
    forced = TpuShuffleConf({
        "spark.shuffle.tpu.dispatcherCpuList": "3",
        "spark.shuffle.tpu.coreCensus": 8,
    })
    assert forced.core_census == 8
    assert forced.decode_threads == 4

    # garbage pin spec expands to all cores — not a pin, use the mask
    garbage = TpuShuffleConf({"spark.shuffle.tpu.dispatcherCpuList": "zzz"})
    assert garbage.core_census == 16

    # explicit per-key settings still win over any census
    explicit = TpuShuffleConf({
        "spark.shuffle.tpu.dispatcherCpuList": "3",
        "spark.shuffle.tpu.decodeThreads": 2,
        "spark.shuffle.tpu.tierPrefetch": "true",
    })
    assert explicit.decode_threads == 2
    assert explicit.tier_prefetch is True


def test_core_census_affinity_mask(monkeypatch):
    """The census reads the scheduler-affinity mask, not the machine
    count — a taskset/cgroup-limited process sizes itself by what it
    can actually run on."""
    import sparkrdma_tpu.conf as conf_mod

    monkeypatch.setattr(conf_mod.os, "cpu_count", lambda: 64)
    monkeypatch.setattr(
        conf_mod.os, "sched_getaffinity", lambda pid: {0, 1}, raising=False
    )
    assert conf_mod.host_core_census() == 2
    assert TpuShuffleConf().core_census == 2
