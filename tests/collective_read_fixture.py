"""TEST FIXTURE — the in-process opportunistic collective read plane.

Superseded by the unified windowed plane (``readPlane=windowed``,
shuffle/bulk.py WindowedReadPlane), which is reactive AND
multi-process: cross-process agreement on collective launches comes
from the driver's window plans instead of this module's per-process
batching, so a pod job gets both properties at once.  Production
configs that ask for ``readPlane=collective`` are routed to the
windowed plane; tests opt into this fixture by passing an explicit
``CollectiveNetwork`` to ``TpuShuffleContext`` (arena/ODP mechanics
are still exercised here and in tests/test_lazy_staging.py).

Original design: grouped block fetches executed as all_to_all tile
rounds over the device mesh.

This is the integration the north star demands (SURVEY.md §7 "One-sided
READ pull model", VERDICT round-1 item 1): the control plane still
resolves exact (mkey, offset, length) locations and the reader still
groups and windows fetches — but a fetch against a mesh-resident
executor no longer reads host bytes.  Instead the
:class:`ExchangeCoordinator` batches every pending fetch, packs the
requested byte ranges out of each source device's persistent HBM arena
with ONE on-device row gather, and moves them in synchronized
``all_to_all`` rounds over ICI; each destination pulls its round shard
once (no per-block host round-trips — the reference's scatter RDMA READ
into one registered buffer, RdmaChannel.java:441-474, inverted into
SPMD collectives).

Mechanics:

- Arenas are uint8 HBM arrays of one fixed capacity per device
  (memory/device_arena.py); blocks are 128-byte-aligned spans, so the
  pack gathers int8x128 ROWS (byte-granular gathers are ~100x slower
  on the MXU-less gather path; row gathers move cache-line-sized
  chunks).  Commit paths pad partition offsets to ``ROW_BYTES``.
- One jitted ``shard_map`` program per (arena rows, D, round rows):
  ``take`` the requested rows → ``all_to_all`` → destination-sharded
  [D_dst, D_src, C_rows, ROW] output.  The arena shape is fixed by
  conf, so the program cache stays tiny.
- Fetches accumulate for ``flush_ms`` (or until an explicit flush) —
  the tile-round scheduling window that plays the reference's
  ``maxBytesInFlight`` aggregation role on the collective plane.
- Blocks that are NOT arena-resident on a mesh device (file-backed or
  lazily staged segments, pre-arena commits) transparently fall back
  to the host read path.
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.memory.device_arena import ROW_BYTES
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh
from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelState,
    ChannelType,
    CompletionListener,
    TransportError,
)
from sparkrdma_tpu.transport.loopback import LoopbackNetwork
from sparkrdma_tpu.transport.node import Address, Node
from sparkrdma_tpu.utils.types import BlockLocation

logger = logging.getLogger(__name__)


@functools.lru_cache(maxsize=32)
def _pack_a2a_fn(mesh, arena_rows: int, n_devices: int, c_rows: int):
    """Jitted pack+exchange: per device, gather its requested rows and
    all_to_all them.  arena: [D*AR, ROW] sharded by source on dim 0 —
    the 2-D shape each DeviceArena holds natively, so flush hands XLA
    the resident buffers with no relayout; idx: [D, D, C] row indices
    sharded by source; out: [D, D, C, ROW] sharded by DESTINATION
    (out[d, s] = rows src s sent dst d)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec_arena = P(EXCHANGE_AXIS, None)
    spec_idx = P(EXCHANGE_AXIS, None, None)
    spec_out = P(EXCHANGE_AXIS, None, None, None)

    def body(arena, idx):  # local: [AR, ROW], [1, D, C]
        tile = jnp.take(arena, idx[0].reshape(-1), axis=0)
        tile = tile.reshape(n_devices, c_rows, ROW_BYTES)
        y = jax.lax.all_to_all(
            tile[None], EXCHANGE_AXIS, split_axis=1, concat_axis=0
        )  # [D, 1, C, ROW]: row s = tile from source s
        return jnp.swapaxes(y, 0, 1)  # [1, D, C, ROW]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec_arena, spec_idx),
        out_specs=spec_out,
    )
    fn = jax.jit(mapped)
    return fn, (
        NamedSharding(mesh, spec_arena),
        NamedSharding(mesh, spec_idx),
    )


class _Request:
    __slots__ = ("src", "dst", "ranges", "lengths", "listener")

    def __init__(self, src: int, dst: int,
                 ranges: List[Tuple[int, int]], listener):
        self.src = src
        self.dst = dst
        self.ranges = ranges  # [(absolute arena offset, exact length)]
        self.lengths = [n for _, n in ranges]
        self.listener = listener


class ExecutorEntry:
    """One mesh-resident executor known to the coordinator."""

    __slots__ = ("address", "device_index", "arena_manager", "device_arena",
                 "resolver")

    def __init__(self, address, device_index, arena_manager, device_arena,
                 resolver=None):
        self.address = address
        self.device_index = device_index
        self.arena_manager = arena_manager
        self.device_arena = device_arena
        # lazy-staging hook: lets the coordinator fault host-committed
        # segments into the arena on first device-plane touch (ODP)
        self.resolver = resolver


class ExchangeCoordinator:
    """Batches block fetches into pack+all_to_all rounds."""

    def __init__(self, mesh=None, tile_bytes: int = 4 << 20,
                 flush_ms: float = 2.0):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.devices = list(self.mesh.devices.flat)
        self.n_devices = len(self.devices)
        self.tile_rows = max(1, int(tile_bytes) // ROW_BYTES)
        self.flush_ms = flush_ms
        self._entries: Dict[int, ExecutorEntry] = {}  # device_index →
        # zero arenas standing in for unattached mesh devices (symmetric
        # collective participation), created once per (device, shape)
        self._placeholders: Dict[Tuple[int, int], object] = {}
        self._pending: List[_Request] = []
        self._lock = threading.Lock()
        # rounds are globally ordered collective launches: concurrent
        # multi-device dispatches from different threads stall XLA's
        # cross-device rendezvous, so exactly ONE round runs at a time —
        # fetches submitted meanwhile merge into the next (fuller) batch
        self._exec_lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._stopped = False
        # stats (reader-stats analog for the collective plane)
        self.rounds_executed = 0
        self.batches_executed = 0
        self.payload_bytes_moved = 0
        self.padded_bytes_moved = 0
        self.fallback_blocks = 0

    # -- membership ---------------------------------------------------------
    def attach(self, entry: ExecutorEntry) -> None:
        with self._lock:
            if entry.device_index in self._entries:
                raise ValueError(
                    f"device {entry.device_index} already attached"
                )
            self._entries[entry.device_index] = entry

    def detach(self, device_index: int) -> None:
        with self._lock:
            self._entries.pop(device_index, None)

    def entry_for(self, address) -> Optional[ExecutorEntry]:
        with self._lock:
            for e in self._entries.values():
                if e.address == address:
                    return e
        return None

    # -- submission ---------------------------------------------------------
    def submit(
        self,
        src_entry: ExecutorEntry,
        dst_entry: ExecutorEntry,
        locations: Sequence[BlockLocation],
        listener: CompletionListener,
        fallback_read: Callable[[Sequence[BlockLocation]], List],
    ) -> None:
        """Enqueue one grouped fetch (data flows src → dst).  Falls
        back to ``fallback_read`` (host path) when any block is not
        arena-resident row-aligned on the source device."""
        ranges: List[Tuple[int, int]] = []
        for loc in locations:
            rng = self._resolve(src_entry, loc)
            if rng is None:
                self.fallback_blocks += len(locations)
                self._run_fallback(locations, listener, fallback_read)
                return
            ranges.append(rng)
        req = _Request(
            src_entry.device_index, dst_entry.device_index, ranges, listener
        )
        with self._lock:
            if self._stopped:
                raise TransportError("coordinator stopped")
            self._pending.append(req)
            if self._timer is None:
                self._timer = threading.Timer(
                    self.flush_ms / 1000.0, self._flush_timer
                )
                self._timer.daemon = True
                self._timer.start()

    @staticmethod
    def _resolve(entry: ExecutorEntry,
                 loc: BlockLocation) -> Optional[Tuple[int, int]]:
        """BlockLocation → absolute (arena offset, length), or None when
        the block can't ride the collective plane.  A host-committed
        lazy segment is staged into the arena here — the first
        device-plane touch IS the registration, exactly ODP's
        page-fault semantics (RdmaBufferManager.java:103-110)."""
        seg = entry.arena_manager.get(loc.mkey)
        span = getattr(seg, "span", None)
        if span is None and entry.resolver is not None:
            try:
                seg = entry.resolver.ensure_staged(loc.mkey)
            except MemoryError:
                logger.warning(
                    "lazy staging of mkey=%d skipped (arena full)",
                    loc.mkey,
                )
                seg = None
            span = getattr(seg, "span", None)
        if span is None or span.arena is not entry.device_arena:
            return None
        abs_off = span.offset + loc.address
        if abs_off % ROW_BYTES != 0:
            return None  # unaligned commit: host path
        return abs_off, loc.length

    @staticmethod
    def _run_fallback(locations, listener, fallback_read) -> None:
        try:
            blocks = fallback_read(locations)
        except BaseException as e:
            try:
                listener.on_failure(e)
            except BaseException:
                pass
        else:
            try:
                listener.on_success(blocks)
            except BaseException:
                pass

    # -- execution ----------------------------------------------------------
    def _flush_timer(self) -> None:
        try:
            self.flush()
        except BaseException:
            logger.exception("collective flush failed")

    def flush(self) -> None:
        """Run all pending fetches as one batched exchange."""
        with self._exec_lock:
            with self._lock:
                if self._timer is not None:
                    self._timer.cancel()
                    self._timer = None
                batch, self._pending = self._pending, []
                entries = dict(self._entries)
            if not batch:
                return
            try:
                self._execute(batch, entries)
            except BaseException as e:
                logger.exception("collective exchange batch failed")
                for req in batch:
                    try:
                        req.listener.on_failure(e)
                    except BaseException:
                        pass

    def _execute(self, batch: List[_Request],
                 entries: Dict[int, ExecutorEntry]) -> None:
        import jax

        D = self.n_devices
        # stream layout: per (s, d) pair, requests in order, each block
        # occupying ceil(len/ROW) row slots
        stream_rows = np.zeros((D, D), np.int64)
        by_pair: Dict[Tuple[int, int], List[_Request]] = {}
        for req in batch:
            rows = sum(
                (n + ROW_BYTES - 1) // ROW_BYTES for n in req.lengths
            )
            stream_rows[req.src, req.dst] += rows
            by_pair.setdefault((req.src, req.dst), []).append(req)
        max_rows = int(stream_rows.max())
        if max_rows == 0:
            for req in batch:
                req.listener.on_success([b""] * len(req.ranges))
            return
        c_rows = min(self.tile_rows, max_rows)
        # pow2 quantize so the jit cache stays small across batches
        c_rows = 1 << (c_rows - 1).bit_length()
        rounds = (max_rows + c_rows - 1) // c_rows

        # per-source row-index streams [D, total_cols]
        total_cols = rounds * c_rows
        idx_np = np.zeros((D, D, total_cols), np.int32)
        for (s, d), reqs in by_pair.items():
            cursor = 0
            for req in reqs:
                for off, n in req.ranges:
                    r0 = off // ROW_BYTES
                    nr = (n + ROW_BYTES - 1) // ROW_BYTES
                    idx_np[s, d, cursor : cursor + nr] = np.arange(
                        r0, r0 + nr, dtype=np.int32
                    )
                    cursor += nr

        arena_caps = {
            e.device_arena.capacity for e in entries.values()
            if e.device_arena is not None
        }
        if len(arena_caps) != 1:
            raise TransportError(
                f"device arenas must share one capacity, got {arena_caps}"
            )
        arena_rows = arena_caps.pop() // ROW_BYTES
        fn, (arena_sharding, idx_sharding) = _pack_a2a_fn(
            self.mesh, arena_rows, D, c_rows
        )

        # destination accumulation buffers
        out_streams: Dict[Tuple[int, int], np.ndarray] = {
            (s, d): np.empty(int(stream_rows[s, d]) * ROW_BYTES, np.uint8)
            for (s, d) in by_pair
        }

        arenas = [
            entries[i].device_arena if i in entries else None
            for i in range(D)
        ]
        locks = [a._lock for a in arenas if a is not None]
        for r in range(rounds):
            lo = r * c_rows
            # dispatch under every arena lock: a donated commit write
            # must not invalidate a handle between capture and dispatch
            for lk in locks:
                lk.acquire()
            try:
                shards = []
                idx_shards = []
                for i, dev in enumerate(self.devices):
                    a = arenas[i]
                    if a is not None:
                        arr = a.array  # natively [AR, ROW] on dev
                    else:
                        key = (i, arena_rows)
                        arr = self._placeholders.get(key)
                        if arr is None:
                            import jax.numpy as jnp

                            with jax.default_device(dev):
                                arr = jnp.zeros(
                                    (arena_rows, ROW_BYTES), jnp.uint8
                                )
                            self._placeholders[key] = arr
                    shards.append(jax.device_put(arr, dev))
                    idx_shards.append(jax.device_put(
                        idx_np[i : i + 1, :, lo : lo + c_rows], dev
                    ))
                arena_g = jax.make_array_from_single_device_arrays(
                    (D * arena_rows, ROW_BYTES), arena_sharding, shards
                )
                idx_g = jax.make_array_from_single_device_arrays(
                    (D, D, c_rows), idx_sharding, idx_shards
                )
                out = fn(arena_g, idx_g)
            finally:
                for lk in reversed(locks):
                    lk.release()
            self.rounds_executed += 1
            self.padded_bytes_moved += D * D * c_rows * ROW_BYTES
            # each destination pulls its shard ONCE per round
            for shard in out.addressable_shards:
                d = shard.index[0].start
                d = 0 if d is None else d
                if not any(dd == d for (_, dd) in by_pair):
                    continue
                local = np.asarray(shard.data)[0]  # [D_src, c_rows, ROW]
                for (s, dd) in by_pair:
                    if dd != d:
                        continue
                    n_rows = int(stream_rows[s, d])
                    take_lo = min(lo, n_rows)
                    take_hi = min(lo + c_rows, n_rows)
                    if take_hi <= take_lo:
                        continue
                    dst_buf = out_streams[(s, d)]
                    dst_buf[
                        take_lo * ROW_BYTES : take_hi * ROW_BYTES
                    ] = local[s, : take_hi - take_lo].reshape(-1)

        from sparkrdma_tpu.utils.trace import get_tracer

        get_tracer().instant(
            "collective.batch",
            requests=len(batch), rounds=rounds, c_rows=c_rows,
            payload_bytes=sum(sum(r.lengths) for r in batch),
        )
        # slice per-request blocks out of the accumulated streams
        for (s, d), reqs in by_pair.items():
            stream = out_streams[(s, d)]
            cursor = 0
            for req in reqs:
                blocks = []
                for _, n in req.ranges:
                    nr = (n + ROW_BYTES - 1) // ROW_BYTES
                    start = cursor * ROW_BYTES
                    blocks.append(stream[start : start + n])
                    cursor += nr
                self.payload_bytes_moved += sum(req.lengths)
                try:
                    req.listener.on_success(blocks)
                except BaseException:
                    logger.exception("fetch listener raised")
        self.batches_executed += 1

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            pending, self._pending = self._pending, []
        err = TransportError("coordinator stopped")
        for req in pending:
            try:
                req.listener.on_failure(err)
            except BaseException:
                pass

    def stats(self) -> Dict[str, int]:
        return {
            "rounds_executed": self.rounds_executed,
            "batches_executed": self.batches_executed,
            "payload_bytes_moved": self.payload_bytes_moved,
            "padded_bytes_moved": self.padded_bytes_moved,
            "fallback_blocks": self.fallback_blocks,
        }


class CollectiveChannel(Channel):
    """READ channel whose scatter reads ride the coordinator.  RPC is
    not served here — control frames use the loopback RPC channels,
    exactly the reference's RPC/READ role split (RdmaChannel.java:41).

    The collective-vs-host decision happens PER READ (executors attach
    to mesh devices lazily, after hello/announce may already have
    pre-connected channels): when both endpoints are mesh-attached the
    fetch rides the coordinator, otherwise it is a one-sided host read
    off the peer's block stores, async like the loopback backend."""

    def __init__(self, local: Node, remote: Node, network,
                 coordinator: ExchangeCoordinator, send_queue_depth: int):
        super().__init__(ChannelType.READ_REQUESTOR, send_queue_depth)
        self.local = local
        self.remote = remote
        self.network = network
        self.coordinator = coordinator
        self._set_state(ChannelState.CONNECTED)

    def _post_rpc(self, frames, listener) -> None:
        raise TransportError("RPC not supported on a collective READ channel")

    def _check_alive(self) -> None:
        if self.network.is_partitioned(self.local.address,
                                       self.remote.address):
            raise TransportError(
                f"network partition to {self.remote.address}"
            )
        if self.state != ChannelState.CONNECTED:
            raise TransportError("channel not connected")

    def _post_read(self, locations, listener) -> None:
        from sparkrdma_tpu.transport.channel import FnCompletionListener

        def on_success(blocks):
            self._complete(listener, blocks)
            self._release_budget()

        def on_failure(err):
            self._error(err)
            self._fail(listener, err)
            self._release_budget()

        def fallback(locs):
            # host path: one-sided read from the peer's block stores
            self._check_alive()
            return self.remote.read_local_blocks(locs)

        def deliver():
            try:
                self._check_alive()
                src_entry = self.coordinator.entry_for(self.remote.address)
                dst_entry = self.coordinator.entry_for(self.local.address)
                fl = FnCompletionListener(on_success, on_failure)
                if src_entry is None or dst_entry is None:
                    ExchangeCoordinator._run_fallback(
                        locations, fl, fallback
                    )
                else:
                    self.coordinator.submit(
                        src_entry, dst_entry, locations, fl, fallback
                    )
            except BaseException as e:
                on_failure(e)

        self.local.submit(deliver)


class CollectiveNetwork(LoopbackNetwork):
    """Loopback control plane + collective bulk plane.

    Executors attach with a mesh device index; READ channels between
    two attached executors become :class:`CollectiveChannel`s, every
    other channel (RPC, reads involving unattached peers) stays on the
    loopback paths."""

    def __init__(self, mesh=None, tile_bytes: int = 4 << 20,
                 flush_ms: float = 2.0):
        super().__init__()
        self.mesh = mesh if mesh is not None else make_mesh()
        self.coordinator = ExchangeCoordinator(
            self.mesh, tile_bytes=tile_bytes, flush_ms=flush_ms
        )

    def attach_executor(self, manager, device_index: int) -> "ExecutorEntry":
        """Bind an executor manager to a mesh device: creates its
        persistent device arena and routes its future commits + reads
        through the collective plane."""
        from sparkrdma_tpu.memory.device_arena import DeviceArena

        devices = self.coordinator.devices
        if not 0 <= device_index < len(devices):
            raise ValueError(
                f"device index {device_index} outside mesh of {len(devices)}"
            )
        arena = DeviceArena(
            manager.conf.device_arena_bytes, devices[device_index]
        )
        manager.device_arena = arena
        manager.resolver.device_arena = arena
        entry = ExecutorEntry(
            manager.node.address, device_index, manager.arena, arena,
            resolver=manager.resolver,
        )
        self.coordinator.attach(entry)
        return entry

    def connect(self, src: Node, peer: Address, channel_type: ChannelType):
        if channel_type == ChannelType.READ_REQUESTOR:
            remote = self.lookup(peer)
            if remote is not None and not self.is_partitioned(
                src.address, peer
            ):
                ch = CollectiveChannel(
                    src, remote, self, self.coordinator,
                    src.conf.send_queue_depth,
                )
                remote.register_passive_channel(ch)
                return ch
        return super().connect(src, peer, channel_type)

    def stop(self) -> None:
        stats = self.coordinator.stats()
        if stats["batches_executed"]:
            logger.info("collective read plane at stop: %s", stats)
        self.coordinator.stop()
