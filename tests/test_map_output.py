"""MapTaskOutput: partial fills, futures, range serialization
(SURVEY.md §2, RdmaMapTaskOutput)."""

import pytest

from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.utils.types import LOCATION_ENTRY_SIZE, BlockLocation


def test_put_and_get():
    mto = MapTaskOutput(4)
    loc = BlockLocation(1000, 64, 3)
    mto.put(2, loc)
    assert mto.get_location(2) == loc
    assert mto.get_location(0) == BlockLocation.EMPTY


def test_fill_future_resolves_only_when_complete():
    mto = MapTaskOutput(3)
    assert not mto.is_complete
    mto.put(0, BlockLocation(0, 1, 1))
    mto.put(1, BlockLocation(1, 1, 1))
    assert not mto.fill_future.done()
    mto.put(2, BlockLocation(2, 1, 1))
    assert mto.fill_future.done()
    assert mto.fill_future.result(timeout=0) is mto


def test_put_range_roundtrip():
    src = MapTaskOutput(8)
    for p in range(8):
        src.put(p, BlockLocation(p * 100, p + 1, 9))
    dst = MapTaskOutput(8)
    # install in two sub-range chunks, out of order
    dst.put_range(4, 7, src.get_range_bytes(4, 7))
    assert not dst.is_complete
    dst.put_range(0, 3, src.get_range_bytes(0, 3))
    assert dst.is_complete
    for p in range(8):
        assert dst.get_location(p) == src.get_location(p)


def test_get_locations_and_total_bytes():
    mto = MapTaskOutput(5)
    for p in range(5):
        mto.put(p, BlockLocation(p, 10 * (p + 1), 1))
    locs = mto.get_locations(1, 3)
    assert [l.length for l in locs] == [20, 30, 40]
    assert mto.total_bytes() == 10 + 20 + 30 + 40 + 50


def test_range_checks():
    mto = MapTaskOutput(4)
    with pytest.raises(IndexError):
        mto.put(4, BlockLocation.EMPTY)
    with pytest.raises(IndexError):
        mto.get_location(-1)
    with pytest.raises(ValueError):
        mto.put_range(0, 1, b"\x00" * (3 * LOCATION_ENTRY_SIZE))
    with pytest.raises(ValueError):
        MapTaskOutput(0)


def test_duplicate_fills_do_not_fake_completion():
    # reviewer finding: re-delivered publish segments must not double-count
    mto = MapTaskOutput(3)
    mto.put(0, BlockLocation(0, 1, 1))
    mto.put(0, BlockLocation(0, 2, 1))  # retry / re-delivery
    mto.put_range(0, 1, mto.get_range_bytes(0, 1))  # overlapping range
    assert not mto.is_complete
    mto.put(1, BlockLocation(1, 1, 1))
    mto.put(2, BlockLocation(2, 1, 1))
    assert mto.is_complete
