"""MapTaskOutput: partial fills, futures, range serialization
(SURVEY.md §2, RdmaMapTaskOutput)."""

import pytest

from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.utils.types import LOCATION_ENTRY_SIZE, BlockLocation


def test_put_and_get():
    mto = MapTaskOutput(4)
    loc = BlockLocation(1000, 64, 3)
    mto.put(2, loc)
    assert mto.get_location(2) == loc
    assert mto.get_location(0) == BlockLocation.EMPTY


def test_fill_future_resolves_only_when_complete():
    mto = MapTaskOutput(3)
    assert not mto.is_complete
    mto.put(0, BlockLocation(0, 1, 1))
    mto.put(1, BlockLocation(1, 1, 1))
    assert not mto.fill_future.done()
    mto.put(2, BlockLocation(2, 1, 1))
    assert mto.fill_future.done()
    assert mto.fill_future.result(timeout=0) is mto


def test_put_range_roundtrip():
    src = MapTaskOutput(8)
    for p in range(8):
        src.put(p, BlockLocation(p * 100, p + 1, 9))
    dst = MapTaskOutput(8)
    # install in two sub-range chunks, out of order
    dst.put_range(4, 7, src.get_range_bytes(4, 7))
    assert not dst.is_complete
    dst.put_range(0, 3, src.get_range_bytes(0, 3))
    assert dst.is_complete
    for p in range(8):
        assert dst.get_location(p) == src.get_location(p)


def test_get_locations_and_total_bytes():
    mto = MapTaskOutput(5)
    for p in range(5):
        mto.put(p, BlockLocation(p, 10 * (p + 1), 1))
    locs = mto.get_locations(1, 3)
    assert [l.length for l in locs] == [20, 30, 40]
    assert mto.total_bytes() == 10 + 20 + 30 + 40 + 50


def test_range_checks():
    mto = MapTaskOutput(4)
    with pytest.raises(IndexError):
        mto.put(4, BlockLocation.EMPTY)
    with pytest.raises(IndexError):
        mto.get_location(-1)
    with pytest.raises(ValueError):
        mto.put_range(0, 1, b"\x00" * (3 * LOCATION_ENTRY_SIZE))
    with pytest.raises(ValueError):
        MapTaskOutput(0)


def test_duplicate_fills_do_not_fake_completion():
    # reviewer finding: re-delivered publish segments must not double-count
    mto = MapTaskOutput(3)
    mto.put(0, BlockLocation(0, 1, 1))
    mto.put(0, BlockLocation(0, 2, 1))  # retry / re-delivery
    mto.put_range(0, 1, mto.get_range_bytes(0, 1))  # overlapping range
    assert not mto.is_complete
    mto.put(1, BlockLocation(1, 1, 1))
    mto.put(2, BlockLocation(2, 1, 1))
    assert mto.is_complete


def test_take_delta_first_publish_is_whole_table():
    mto = MapTaskOutput(16)
    for p in range(16):
        mto.put(p, BlockLocation(p * 100, p + 1, 7))
    epoch, runs = mto.take_delta()
    assert epoch == 0
    assert runs == [(0, 15, mto.get_range_bytes(0, 15))]
    # nothing changed since: the next delta is empty and the epoch
    # does not advance
    assert mto.take_delta() == (1, [])
    assert mto.take_delta() == (1, [])


def test_take_delta_returns_only_changed_runs():
    mto = MapTaskOutput(64)
    for p in range(64):
        mto.put(p, BlockLocation(p * 100, p + 1, 7))
    mto.take_delta()  # publish 0: everything
    # relocate two disjoint runs
    mto.put(5, BlockLocation(9999, 6, 8))
    mto.put(6, BlockLocation(10005, 7, 8))
    mto.put(40, BlockLocation(20000, 41, 8))
    epoch, runs = mto.take_delta()
    assert epoch == 1
    assert [(f, l) for f, l, _raw in runs] == [(5, 6), (40, 40)]
    assert sum(len(raw) for _f, _l, raw in runs) == 3 * LOCATION_ENTRY_SIZE
    assert runs[0][2] == mto.get_range_bytes(5, 6)


def test_put_range_epoch_guard_rejects_stale_segments():
    """Segments of different publish generations may apply out of
    order (the receive dispatcher is a pool): a stale full-range
    epoch-0 segment must not clobber entries a later epoch-1 delta
    already installed."""
    src = MapTaskOutput(8)
    for p in range(8):
        src.put(p, BlockLocation(p * 100, p + 1, 9))
    stale_full = src.get_range_bytes(0, 7)
    src.put(3, BlockLocation(7777, 4, 10))  # the relocation
    fresh_delta = src.get_range_bytes(3, 3)

    dst = MapTaskOutput(8)
    dst.put_range(3, 3, fresh_delta, epoch=1)   # delta lands FIRST
    dst.put_range(0, 7, stale_full, epoch=0)    # stale full publish
    assert dst.is_complete
    assert dst.get_location(3) == BlockLocation(7777, 4, 10)
    for p in (0, 1, 2, 4, 5, 6, 7):
        assert dst.get_location(p) == BlockLocation(p * 100, p + 1, 9)
    # in-order application converges to the same table
    dst2 = MapTaskOutput(8)
    dst2.put_range(0, 7, stale_full, epoch=0)
    dst2.put_range(3, 3, fresh_delta, epoch=1)
    for p in range(8):
        assert dst2.get_location(p) == dst.get_location(p)
