"""Decode-ahead reduce pipeline (shuffle/decode.py): bit-exact
pipelined-vs-serial sweep across serializer modes, key-ordering
guarantees, failure propagation with no hung workers, byte-credit
bounding, and a lockDebug stress pass with the decode pool active."""

import threading
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.decode import DecodePool
from sparkrdma_tpu.shuffle.manager import (
    ColumnarAggregator,
    TpuShuffleManager,
)
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import FetchFailedError
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.utils.dbglock import get_lock_factory
from sparkrdma_tpu.utils.serde import (
    ColumnarSerializer,
    CompressedSerializer,
    FrameTooLargeError,
    PickleSerializer,
)

BASE_PORT = 47100
_NEXT_PORT = [BASE_PORT]

# serializer conf fragments for the sweep modes
MODES = {
    "pickle": {},
    "columnar": {"spark.shuffle.tpu.serializer": "columnar"},
    "compressed": {"spark.shuffle.tpu.compress": True},
    "compressed-columnar": {
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.compress": True,
    },
}


def _ports(n=1):
    p = _NEXT_PORT[0]
    _NEXT_PORT[0] += 200
    return p


def _run_shuffle(extra_conf, records_per_map, num_parts=4,
                 aggregator=None, map_side_combine=False,
                 key_ordering=False, num_executors=2):
    """One full write→publish→fetch→read cycle on a fresh loopback
    cluster; returns the per-partition outputs in read order."""
    base = _ports()
    net = LoopbackNetwork()
    conf_map = {
        "spark.shuffle.tpu.driverPort": base,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
    }
    conf_map.update(extra_conf)
    conf = TpuShuffleConf(conf_map)
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base + 20 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(num_executors)
    ]
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == num_executors for e in executors):
                break
            time.sleep(0.01)
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(
            7, len(records_per_map), part, aggregator=aggregator,
            map_side_combine=map_side_combine, key_ordering=key_ordering,
        )
        maps_by_host = defaultdict(list)
        for m, records in enumerate(records_per_map):
            ex = executors[m % num_executors]
            w = ex.get_writer(handle, m)
            w.write(records)
            w.stop(True)
            maps_by_host[ex.local_smid].append(m)
        out = []
        for pid in range(num_parts):
            reader = executors[pid % num_executors].get_reader(
                handle, pid, pid + 1, dict(maps_by_host)
            )
            out.append(list(reader.read()))
        return out
    finally:
        for m in executors + [driver]:
            m.stop()


def _records(n, unique_keys=True, seed=0):
    # int keys + int vals pack into columns and pickle alike
    if unique_keys:
        return [((i * 2654435761 + seed) % (10 * n), i) for i in range(n)]
    return [((i * 31 + seed) % 61, i) for i in range(n)]


# -- bit-exact pipelined-vs-serial sweep --------------------------------------

@pytest.mark.parametrize("mode", sorted(MODES))
def test_bitexact_sort_sweep(mode):
    """key_ordering with unique keys: the fully-deterministic output —
    decodeThreads 0 (legacy serial), 1 and 4 must produce EXACTLY the
    same per-partition sequences (stable per-block sort + stable k-way
    merge == stable global sort)."""
    records_per_map = [_records(700, seed=m) for m in range(3)]
    outs = {}
    for threads in (0, 1, 4):
        conf = dict(MODES[mode])
        conf["spark.shuffle.tpu.decodeThreads"] = threads
        outs[threads] = _run_shuffle(
            conf, records_per_map, key_ordering=True
        )
    assert outs[1] == outs[0], f"{mode}: decodeThreads=1 diverged"
    assert outs[4] == outs[0], f"{mode}: decodeThreads=4 diverged"
    # and the output really is the key-sorted multiset of the input
    per_part = defaultdict(list)
    part = HashPartitioner(4)
    for recs in records_per_map:
        for k, v in recs:
            per_part[part.partition(k)].append((k, v))
    for pid in range(4):
        assert outs[0][pid] == sorted(per_part[pid], key=lambda kv: kv[0])


@pytest.mark.parametrize("mode", sorted(MODES))
def test_bitexact_reduce_sweep(mode):
    """Reducing aggregator (+ key ordering → deterministic sequence):
    the decode workers pre-combine columnar batches; sums must match
    the serial path exactly."""
    agg = ColumnarAggregator.reduce("sum")
    records_per_map = [
        _records(600, unique_keys=False, seed=m) for m in range(3)
    ]
    outs = {}
    for threads in (0, 1, 4):
        conf = dict(MODES[mode])
        conf["spark.shuffle.tpu.decodeThreads"] = threads
        outs[threads] = _run_shuffle(
            conf, records_per_map, aggregator=agg, key_ordering=True
        )
    assert outs[1] == outs[0], f"{mode}: decodeThreads=1 diverged"
    assert outs[4] == outs[0], f"{mode}: decodeThreads=4 diverged"
    expect = defaultdict(int)
    for recs in records_per_map:
        for k, v in recs:
            expect[k] += v
    got = {k: v for pout in outs[0] for k, v in pout}
    assert {k: int(v) for k, v in got.items()} == dict(expect)


def test_group_aggregation_pipelined_matches_serial():
    """Columnar group_by_key through the decode pool: same groups,
    same per-key value multisets."""
    records_per_map = [
        _records(400, unique_keys=False, seed=m) for m in range(3)
    ]
    outs = {}
    for threads in (0, 4):
        conf = dict(MODES["compressed-columnar"])
        conf["spark.shuffle.tpu.decodeThreads"] = threads
        out = _run_shuffle(
            conf, records_per_map,
            aggregator=ColumnarAggregator.group(),
        )
        outs[threads] = {
            k: sorted(list(v) if hasattr(v, "__len__") else [v])
            for pout in out for k, v in pout
        }
    assert outs[4] == outs[0]


def test_bitexact_split_spilled_blocks():
    """The composite-ticket merge regression: a SPILLED map output is a
    byte-concatenation of independently sorted spill chunks, so a
    >=1MiB block that splits at frame boundaries must MERGE its
    fragment sorts (not concatenate them) to stay bit-exact with the
    serial global sort."""
    rng_vals = "x" * 46
    records_per_map = [
        [((i * 2654435761 + m) % (1 << 30), rng_vals + str(i))
         for i in range(44_000)]
        for m in range(2)
    ]
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    try:
        outs = {}
        for threads in (0, 4):
            conf = {
                "spark.shuffle.tpu.decodeThreads": threads,
                # several spill chunks per map → multi-run blocks
                "spark.shuffle.tpu.shuffleSpillRecordThreshold": 9000,
                "spark.shuffle.tpu.spillPartitionFiles": 0,
            }
            outs[threads] = _run_shuffle(
                conf, records_per_map, num_parts=2, key_ordering=True
            )
        # the >=1MiB blocks must really have fanned out across workers
        splits = [
            inst for _k, inst in GLOBAL_REGISTRY.instruments()
            if getattr(inst, "name", "")
            == "shuffle_decode_block_splits_total"
        ]
        assert sum(s.value for s in splits) > 0, "split path not engaged"
    finally:
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()
    assert outs[4] == outs[0]
    for pout in outs[4]:
        keys = [k for k, _v in pout]
        assert keys == sorted(keys)


def test_ordering_guarantee_with_duplicate_keys():
    """key_ordering holds under the pipelined path even with heavy key
    duplication (merge correctness, not just the unique-key case)."""
    for mode in ("pickle", "compressed-columnar"):
        conf = dict(MODES[mode])
        conf["spark.shuffle.tpu.decodeThreads"] = 4
        out = _run_shuffle(
            conf,
            [_records(500, unique_keys=False, seed=m) for m in range(3)],
            key_ordering=True,
        )
        for pout in out:
            keys = [k for k, _v in pout]
            assert keys == sorted(keys), mode


# -- failure propagation ------------------------------------------------------

def test_fetch_failure_mid_pipeline_no_hung_workers():
    """A dead remote peer fails the pipelined read with
    FetchFailedError, and the decode pool stays healthy afterwards
    (poisoned stream: queued decodes cancel, credits release)."""
    base = _ports()
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "3s",
        "spark.shuffle.tpu.decodeThreads": 2,
        "spark.shuffle.tpu.compress": True,
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base + 20 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == 2 for e in executors):
                break
            time.sleep(0.01)
        part = HashPartitioner(2)
        handle = driver.register_shuffle(9, 2, part, key_ordering=True)
        maps_by_host = {}
        for m, ex in enumerate(executors):
            w = ex.get_writer(handle, m)
            w.write(_records(400, seed=m))
            w.stop(True)
            maps_by_host[ex.local_smid] = [m]
        # cut the remote peer: executor 0's read of executor 1's block
        # fails mid-pipeline (locations still resolve via the driver)
        net.partition(executors[1].node.address)
        reader = executors[0].get_reader(handle, 0, 1, maps_by_host)
        with pytest.raises(FetchFailedError):
            list(reader.read())
        # the shared pool survived: a fresh stream still decodes
        pool = executors[0].get_decode_pool()
        assert pool is not None
        stream = pool.stream(lambda d: (list(bytes(d)), len(d)))
        t = stream.submit(b"\x01\x02\x03")
        items, n = t.get()
        assert items == [1, 2, 3] and n == 3
        stream.close()
    finally:
        for m in executors + [driver]:
            m.stop()


def test_decode_error_propagates_to_consumer():
    """A decode_fn raising (corrupt frame) re-raises on the task
    thread at get(), and close() leaves no worker stuck."""
    pool = DecodePool("t", 2, 1 << 20)
    try:
        def boom(data):
            raise ValueError("corrupt frame")

        stream = pool.stream(boom)
        t = stream.submit(b"x" * 128)
        with pytest.raises(ValueError, match="corrupt frame"):
            t.get()
        stream.close()
        ok = pool.stream(lambda d: (len(d), 1))
        assert ok.submit(b"abc").get() == (3, 1)
        ok.close()
    finally:
        pool.stop()


def test_close_cancels_queued_and_releases_credits():
    """close() on a stream with queued work: queued tickets cancel,
    held credits return to the budget, workers stay serviceable."""
    gate = threading.Event()

    def slow(data):
        gate.wait(5)
        return data, len(data)

    # budget fits ONE 1 KiB block: the rest queue behind the credits
    pool = DecodePool("t", 2, 1024)
    try:
        stream = pool.stream(slow)
        tickets = [stream.submit(bytes([i]) * 1024) for i in range(6)]
        time.sleep(0.05)  # let a worker take the first credit
        stream.close()
        gate.set()
        # every unconsumed ticket settles (cancelled or decoded); none
        # hang, and the full budget is available again
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with pool._cv:
                if pool._credits == pool._budget:
                    break
            time.sleep(0.01)
        with pool._cv:
            assert pool._credits == pool._budget
        fresh = pool.stream(lambda d: (d, len(d)))
        assert fresh.submit(b"ok").get()[1] == 2
        fresh.close()
        del tickets
    finally:
        pool.stop()


def test_composite_error_discards_siblings_without_decoding():
    """When one fragment of a split block fails, the remaining queued
    fragments are DISCARDED (cancelled), not steal-decoded on the task
    thread, and their credits return."""
    calls = []

    def decode(data):
        calls.append(bytes(data[:1]))
        if bytes(data[:1]) == b"\x00":
            raise ValueError("bad fragment")
        time.sleep(0.2)  # so the lone worker can't out-race the discard
        return [bytes(data)], 1

    # single worker + a gate-free pool: submit the composite parts
    # directly so the first part fails before the rest are admitted
    pool = DecodePool("t", 1, 1 << 20)
    try:
        from sparkrdma_tpu.shuffle.decode import _CompositeTicket

        stream = pool.stream(decode)
        # stall the worker on an unrelated slow ticket so the
        # composite's parts stay queued when get() walks them
        gate = threading.Event()
        slow = pool.stream(lambda d: (gate.wait(5), 1))
        blocker = slow.submit(b"z")
        parts = [stream.submit(bytes([i]) * 64) for i in range(6)]
        comp = _CompositeTicket(parts, 6 * 64)
        gate.set()
        with pytest.raises(ValueError, match="bad fragment"):
            comp.get()
        blocker.get()
        # fragment 0 decoded (and failed); the later QUEUED fragments
        # were cancelled without running decode
        assert b"\x00" in calls
        assert len(calls) < 6, f"siblings were steal-decoded: {calls}"
        # an in-flight abandoned fragment settles at decode completion
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with pool._cv:
                if pool._credits == pool._budget:
                    break
            time.sleep(0.01)
        with pool._cv:
            assert pool._credits == pool._budget
        stream.close()
        slow.close()
    finally:
        pool.stop()


# -- credit bounding ----------------------------------------------------------

def test_credit_bounding_without_deadlock():
    """A budget far smaller than the submitted payload total cannot
    deadlock: consumption in ticket order always drains (oversized
    blocks clamp; unadmitted tickets steal-decode inline)."""
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    pool = DecodePool("t", 3, 2048)  # ~2 blocks of credit
    try:
        stream = pool.stream(lambda d: (len(d), 1))
        tickets = [stream.submit(bytes(1024)) for _ in range(64)]
        # an oversized single block clamps to the whole budget
        tickets.append(stream.submit(bytes(1 << 20)))
        done = []

        def consume():
            for t in tickets:
                done.append(t.get())

        c = threading.Thread(target=consume, daemon=True)
        c.start()
        c.join(timeout=20)
        assert not c.is_alive(), "credit-bounded pipeline deadlocked"
        assert [n for n, _one in done[:64]] == [1024] * 64
        assert done[64][0] == 1 << 20
        stream.close()
        snap = GLOBAL_REGISTRY.snapshot()
        names = {c["name"]: c["value"] for c in snap["counters"]}
        assert names.get("shuffle_decode_tasks_total", 0) >= 65
    finally:
        pool.stop()
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()


def test_frame_split_fans_out_and_preserves_framing():
    """One large compressed block splits at frame boundaries across
    workers; the composite ticket's concatenated result equals the
    whole-block decode exactly."""
    ser = CompressedSerializer(PickleSerializer(batch_size=64),
                              frame_records=64)
    records = _records(3000)
    blob = ser.serialize(records)
    assert len(blob) >= 1 << 20 or len(ser.frame_spans(blob)) > 4

    def decode(data):
        recs = list(ser.deserialize(data))
        return recs, len(recs)

    pool = DecodePool("t", 4, 64 << 20)
    try:
        stream = pool.stream(decode, ser.frame_spans)
        t = stream.submit_block(blob)
        items, n = t.get()
        assert n == len(records)
        assert items == records
        stream.close()
    finally:
        pool.stop()


# -- serde satellites ---------------------------------------------------------

def test_frame_too_large_is_structured():
    ser = CompressedSerializer(PickleSerializer(), min_size=1 << 30)
    ser.MAX_FRAME_BODY = 64  # instance override: no 4 GiB allocation
    with pytest.raises(FrameTooLargeError) as ei:
        ser.serialize([(i, "x" * 50) for i in range(4)])
    err = ei.value
    assert err.frame_bytes > 64
    assert err.record_count == 4
    assert err.frame_records == ser.frame_records
    assert "compressFrameRecords" in str(err)
    assert str(err.record_count) in str(err)
    # structured subclass of the old ValueError contract
    assert isinstance(err, ValueError)


def test_conf_frame_records_reaches_serializer():
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.compress": True,
        "spark.shuffle.tpu.compressFrameRecords": 17,
    })
    net = LoopbackNetwork()
    mgr = TpuShuffleManager(conf, is_driver=True, network=net,
                            port=_ports())
    try:
        assert isinstance(mgr.serializer, CompressedSerializer)
        assert mgr.serializer.frame_records == 17
    finally:
        mgr.stop()


@pytest.mark.parametrize("make", [
    lambda: PickleSerializer(batch_size=32),
    lambda: ColumnarSerializer(),
    lambda: CompressedSerializer(PickleSerializer(batch_size=32),
                                 frame_records=32),
    lambda: CompressedSerializer(ColumnarSerializer(), min_size=16),
])
def test_frame_spans_cover_and_decode_independently(make):
    """frame_spans tile the payload contiguously and every span group
    deserializes standalone to the same record slice."""
    ser = make()
    if isinstance(ser, CompressedSerializer) and getattr(
        ser.inner, "supports_columns", False
    ) or isinstance(ser, ColumnarSerializer):
        from sparkrdma_tpu.utils.columns import ColumnBatch

        blob = b"".join(
            ser.serialize(ColumnBatch.from_records(_records(100, seed=s)))
            for s in range(5)
        )
        expect = [kv for s in range(5) for kv in _records(100, seed=s)]
    else:
        blob = ser.serialize(_records(500))
        expect = _records(500)
    spans = ser.frame_spans(blob)
    assert spans[0][0] == 0 and spans[-1][1] == len(blob)
    for (a, b), (c, _d) in zip(spans, spans[1:]):
        assert b == c, "spans must tile contiguously"
    view = memoryview(blob)
    got = []
    for a, b in spans:
        got.extend(ser.deserialize(view[a:b]))
    assert got == expect


# -- local accounting satellite ----------------------------------------------

def test_local_reads_count_in_wait_split():
    """Loopback-heavy (all-local) reduce: the wire-wait/decode-wait
    split is populated even though no remote fetch ever runs."""
    out_metrics = {}
    base = _ports()
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base,
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    try:
        part = HashPartitioner(2)
        handle = driver.register_shuffle(3, 1, part)
        w = driver.get_writer(handle, 0)
        w.write(_records(2000))
        w.stop(True)
        reader = driver.get_reader(
            handle, 0, 2, {driver.local_smid: [0]}
        )
        out = list(reader.read())
        assert len(out) == 2000
        out_metrics = reader.metrics
        assert out_metrics.local_blocks == 2
        assert out_metrics.remote_blocks == 0
        assert out_metrics.fetch_wait_ms > 0  # local backing-store read
        assert out_metrics.decode_wait_ms > 0  # local decode time
    finally:
        driver.stop()


# -- windowed plane reuses the pool -------------------------------------------

def _windowed_outputs(devices, threads, base_port):
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.bulk import (
        BulkShuffleSession,
        WindowedReadPlane,
    )

    n_exec = 2
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
        "spark.shuffle.tpu.readPlane": "windowed",
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.compress": True,
        "spark.shuffle.tpu.decodeThreads": threads,
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(n_exec)
    ]
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == n_exec for e in executors):
                break
            time.sleep(0.01)
        session = BulkShuffleSession(
            TileExchange(make_mesh(n_exec), tile_bytes=1 << 12), n_exec,
            timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
        )
        for e in executors:
            e.windowed_plane = WindowedReadPlane(e, session=session)
        num_maps, num_parts = 4, 4
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(
            12, num_maps, part, key_ordering=True
        )
        for m in range(num_maps):
            w = executors[m % n_exec].get_writer(handle, m)
            w.write(_records(300, seed=m))
            w.stop(True)
        results = {}
        errors = {}

        def reduce_task(pid):
            try:
                r = executors[pid % n_exec].get_reader(
                    handle, pid, pid + 1, {}
                )
                results[pid] = list(r.read())
                if threads > 0:
                    assert r.metrics.decode_wait_ms >= 0
            except BaseException as e:
                errors[pid] = e

        tasks = [
            threading.Thread(target=reduce_task, args=(pid,), daemon=True)
            for pid in range(num_parts)
        ]
        for t in tasks:
            t.start()
        for t in tasks:
            t.join(timeout=60)
        assert not errors, errors
        return [results[p] for p in range(num_parts)]
    finally:
        for m in executors + [driver]:
            m.stop()


def test_windowed_plane_decode_pipeline_parity(devices):
    """The windowed device plane's reader through the decode pool:
    same key-ordered output as its serial decode, with the pool
    genuinely engaged."""
    prev = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    GLOBAL_REGISTRY.enabled = True
    try:
        serial = _windowed_outputs(devices, 0, _ports())
        piped = _windowed_outputs(devices, 2, _ports())
        assert piped == serial
        for pout in piped:
            keys = [k for k, _v in pout]
            assert keys == sorted(keys)
        decoded = [
            inst for _k, inst in GLOBAL_REGISTRY.instruments()
            if getattr(inst, "name", "") == "shuffle_decode_tasks_total"
        ]
        assert sum(d.value for d in decoded) > 0
    finally:
        GLOBAL_REGISTRY.enabled = prev
        GLOBAL_REGISTRY.reset()


# -- lockDebug stress ---------------------------------------------------------

def test_lockdebug_stress_with_decode_pool():
    """Concurrent pipelined reads under the runtime lock sanitizer:
    zero rank violations with the decode pool active."""
    factory = get_lock_factory()
    prev = factory.enabled
    prev_reg = GLOBAL_REGISTRY.enabled
    GLOBAL_REGISTRY.reset()
    try:
        errors = []

        def run(seed):
            try:
                conf = dict(MODES["compressed-columnar"])
                conf.update({
                    "spark.shuffle.tpu.decodeThreads": 2,
                    "spark.shuffle.tpu.lockDebug": True,
                    "spark.shuffle.tpu.metrics": True,
                })
                out = _run_shuffle(
                    conf,
                    [_records(500, seed=seed + m) for m in range(3)],
                    key_ordering=True,
                )
                assert sum(len(p) for p in out) == 1500
            except BaseException as e:  # surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(s,), daemon=True)
            for s in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        assert not any(t.is_alive() for t in threads), "stress hung"
        assert not errors, errors
        violations = [
            inst for _k, inst in GLOBAL_REGISTRY.instruments()
            if getattr(inst, "name", "") == "lock_rank_violations_total"
        ]
        assert sum(v.value for v in violations) == 0
        # and the pool really ran (the sweep isn't trivially serial)
        decoded = [
            inst for _k, inst in GLOBAL_REGISTRY.instruments()
            if getattr(inst, "name", "") == "shuffle_decode_tasks_total"
        ]
        assert sum(d.value for d in decoded) > 0
    finally:
        factory.enabled = prev
        GLOBAL_REGISTRY.enabled = prev_reg
        GLOBAL_REGISTRY.reset()
