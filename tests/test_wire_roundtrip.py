"""Wire identity: every message type round-trips across empty,
boundary and max-size payloads, and every encoding is pinned bit-exact
against golden frames captured from the PRE-schema-refactor encoders
(tests/data/wire_golden_frames.json) — the schema refactor must be a
pure refactor on the wire."""

import base64
import json
import os

import pytest

from sparkrdma_tpu.rpc.messages import (
    MSG_TYPES,
    AnnounceShuffleManagersMsg,
    CleanShuffleMsg,
    ExchangePlanMsg,
    FetchExchangePlanMsg,
    FetchMapStatusFailedMsg,
    FetchMapStatusMsg,
    FetchMapStatusResponseMsg,
    HeartbeatMsg,
    HelloMsg,
    PrefetchHintMsg,
    PublishMapTaskOutputMsg,
    PublishShuffleMetricsMsg,
    decode_msg,
)
from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
)

GOLDEN_PATH = os.path.join(
    os.path.dirname(__file__), "data", "wire_golden_frames.json"
)

I32_MAX = 2**31 - 1
I32_MIN = -(2**31)


def smid(i: int) -> ShuffleManagerId:
    return ShuffleManagerId(
        f"host{i}.example", 7000 + i,
        BlockManagerId(f"exec-{i}", f"host{i}.example", 8000 + i),
    )


def loc(i: int) -> BlockLocation:
    return BlockLocation(i * 4096, 4096 + i, 100 + i)


# -- golden frames: bit-exact wire identity vs pre-refactor encoders ----------

with open(GOLDEN_PATH) as _f:
    GOLDEN = json.load(_f)


@pytest.mark.parametrize("name", sorted(GOLDEN))
def test_golden_frame_bit_exact(name):
    """decode(golden) must succeed, identify as the recorded class, and
    re-encode to the EXACT pre-refactor bytes.  Segmented records pin
    each segment frame independently."""
    rec = GOLDEN[name]
    frames = (
        [base64.b64decode(f) for f in rec["frames"]]
        if "frames" in rec
        else [base64.b64decode(rec["frame"])]
    )
    for frame in frames:
        msg = decode_msg(frame)
        assert type(msg).__name__ == rec["cls"]
        assert type(msg).MSG_TYPE == rec["type"]
        assert msg.encode() == frame, f"golden frame {name} drifted"


def test_golden_corpus_covers_every_message_type():
    covered = {GOLDEN[name]["type"] for name in GOLDEN}
    assert covered == set(MSG_TYPES), (
        f"golden corpus missing types {set(MSG_TYPES) - covered}"
    )


# -- round-trip property: empty / boundary / max-size per type ----------------

def _big_entries(n):
    buf = bytearray()
    for i in range(n):
        loc(i).write(buf)
    return bytes(buf)


CASES = [
    # HelloMsg
    HelloMsg(smid(1), channel_port=0),
    HelloMsg(smid(1), channel_port=I32_MAX),
    HelloMsg(smid(1), channel_port=-1),
    # AnnounceShuffleManagersMsg
    AnnounceShuffleManagersMsg([]),
    AnnounceShuffleManagersMsg([smid(0)]),
    AnnounceShuffleManagersMsg([smid(i) for i in range(200)]),
    # PublishMapTaskOutputMsg (empty range: last = first - 1)
    PublishMapTaskOutputMsg(
        smid(2), 0, 0, 0, first_reduce_id=0, last_reduce_id=-1, entries=b""
    ),
    PublishMapTaskOutputMsg(
        smid(2), 1, 2, 1, first_reduce_id=0, last_reduce_id=0,
        entries=_big_entries(1),
    ),
    PublishMapTaskOutputMsg(
        smid(2), I32_MAX, I32_MAX, 4096, first_reduce_id=0,
        last_reduce_id=4095, entries=_big_entries(4096), epoch=I32_MAX,
    ),
    # FetchMapStatusMsg
    FetchMapStatusMsg(smid(3), smid(4), 0, 0, block_ids=[]),
    FetchMapStatusMsg(
        smid(3), smid(4), I32_MAX, I32_MAX,
        block_ids=[(I32_MAX, I32_MIN)],
    ),
    FetchMapStatusMsg(
        smid(3), smid(4), 1, 2,
        block_ids=[(m, r) for m in range(64) for r in range(64)],
    ),
    # FetchMapStatusResponseMsg
    FetchMapStatusResponseMsg(0, 0, 0, locations=[]),
    FetchMapStatusResponseMsg(
        I32_MAX, 1, 0,
        locations=[BlockLocation(2**63 - 1, I32_MAX, I32_MAX)],
    ),
    FetchMapStatusResponseMsg(
        7, 5000, 0, locations=[loc(i) for i in range(5000)]
    ),
    # FetchMapStatusFailedMsg
    FetchMapStatusFailedMsg(0, reason=""),
    FetchMapStatusFailedMsg(I32_MAX, reason="x" * 1024),  # at max_len
    FetchMapStatusFailedMsg(1, reason="shuffle 3 unregistered: hôte"),
    # HeartbeatMsg
    HeartbeatMsg(smid(5), seq=0, is_ack=False),
    HeartbeatMsg(smid(5), seq=I32_MAX, is_ack=True),
    # FetchExchangePlanMsg
    FetchExchangePlanMsg(smid(6), 0, 0, window=-1),
    FetchExchangePlanMsg(smid(6), I32_MAX, I32_MAX, window=I32_MAX),
    # ExchangePlanMsg
    ExchangePlanMsg(0, [], [], []),
    ExchangePlanMsg(
        1, [smid(0)], [2**63 - 1], [((0, 0, 2**63 - 1),)],
        window=0, final=False, my_maps=(0,),
    ),
    ExchangePlanMsg(
        I32_MAX,
        [smid(i) for i in range(3)],
        list(range(9)),
        [
            tuple((m, r, (m + r) * 1024) for m in range(4) for r in range(4)),
            (),
            ((I32_MAX, I32_MIN, -1),),
        ],
        window=I32_MAX, final=True, my_maps=tuple(range(128)),
    ),
    # PublishShuffleMetricsMsg
    PublishShuffleMetricsMsg(smid(7), 0, payload=b""),
    PublishShuffleMetricsMsg(smid(7), 1, payload=b"\x00\xff" * 65536),
    # PrefetchHintMsg
    PrefetchHintMsg(0, locations=[]),
    PrefetchHintMsg(I32_MAX, locations=[loc(i) for i in range(2048)]),
    # CleanShuffleMsg
    CleanShuffleMsg(0),
    CleanShuffleMsg(I32_MAX),
]


@pytest.mark.parametrize(
    "msg", CASES, ids=[f"{type(m).__name__}-{i}" for i, m in enumerate(CASES)]
)
def test_roundtrip(msg):
    frame = msg.encode()
    out = decode_msg(frame)
    assert type(out) is type(msg)
    assert out == msg
    # decode is also a fixed point of encode
    assert out.encode() == frame


def test_roundtrip_cases_cover_every_message_type():
    covered = {type(m).MSG_TYPE for m in CASES}
    assert covered == set(MSG_TYPES)


def test_overlong_reason_truncates_to_max_len():
    msg = FetchMapStatusFailedMsg(9, reason="y" * 5000)
    out = decode_msg(msg.encode())
    assert out.reason == "y" * 1024


def test_location_entry_size_is_wire_constant():
    buf = bytearray()
    loc(0).write(buf)
    assert len(buf) == LOCATION_ENTRY_SIZE == 16
