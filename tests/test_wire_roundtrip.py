"""Wire identity: every message type round-trips across empty,
boundary and max-size payloads, and every encoding is pinned bit-exact
against golden frames captured from the PRE-schema-refactor encoders
(tests/data/wire_golden_frames.json) — the schema refactor must be a
pure refactor on the wire."""

import base64
import json
import os

import pytest

from sparkrdma_tpu.rpc.messages import (
    MSG_TYPES,
    AnnounceShuffleManagersMsg,
    CleanShuffleMsg,
    ExchangePlanMsg,
    FetchExchangePlanMsg,
    FetchMapStatusFailedMsg,
    FetchMapStatusMsg,
    FetchMapStatusResponseMsg,
    FetchMergeStatusMsg,
    HeartbeatMsg,
    HelloMsg,
    MergeStatusResponseMsg,
    PrefetchHintMsg,
    PublishMapTaskOutputMsg,
    PublishShuffleMetricsMsg,
    PushSubBlockMsg,
    decode_msg,
)
from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
)

GOLDEN_PATH = os.path.join(
    os.path.dirname(__file__), "data", "wire_golden_frames.json"
)

I32_MAX = 2**31 - 1
I32_MIN = -(2**31)


def smid(i: int) -> ShuffleManagerId:
    return ShuffleManagerId(
        f"host{i}.example", 7000 + i,
        BlockManagerId(f"exec-{i}", f"host{i}.example", 8000 + i),
    )


def loc(i: int) -> BlockLocation:
    return BlockLocation(i * 4096, 4096 + i, 100 + i)


# -- golden frames: bit-exact wire identity vs pre-refactor encoders ----------

with open(GOLDEN_PATH) as _f:
    GOLDEN = json.load(_f)


@pytest.mark.parametrize("name", sorted(GOLDEN))
def test_golden_frame_bit_exact(name):
    """decode(golden) must succeed, identify as the recorded class, and
    re-encode to the EXACT pre-refactor bytes.  Segmented records pin
    each segment frame independently."""
    rec = GOLDEN[name]
    frames = (
        [base64.b64decode(f) for f in rec["frames"]]
        if "frames" in rec
        else [base64.b64decode(rec["frame"])]
    )
    for frame in frames:
        msg = decode_msg(frame)
        assert type(msg).__name__ == rec["cls"]
        assert type(msg).MSG_TYPE == rec["type"]
        assert msg.encode() == frame, f"golden frame {name} drifted"


def test_golden_corpus_covers_every_message_type():
    covered = {GOLDEN[name]["type"] for name in GOLDEN}
    assert covered == set(MSG_TYPES), (
        f"golden corpus missing types {set(MSG_TYPES) - covered}"
    )


# -- round-trip property: empty / boundary / max-size per type ----------------

def _big_entries(n):
    buf = bytearray()
    for i in range(n):
        loc(i).write(buf)
    return bytes(buf)


CASES = [
    # HelloMsg
    HelloMsg(smid(1), channel_port=0),
    HelloMsg(smid(1), channel_port=I32_MAX),
    HelloMsg(smid(1), channel_port=-1),
    # AnnounceShuffleManagersMsg
    AnnounceShuffleManagersMsg([]),
    AnnounceShuffleManagersMsg([smid(0)]),
    AnnounceShuffleManagersMsg([smid(i) for i in range(200)]),
    # PublishMapTaskOutputMsg (empty range: last = first - 1)
    PublishMapTaskOutputMsg(
        smid(2), 0, 0, 0, first_reduce_id=0, last_reduce_id=-1, entries=b""
    ),
    PublishMapTaskOutputMsg(
        smid(2), 1, 2, 1, first_reduce_id=0, last_reduce_id=0,
        entries=_big_entries(1),
    ),
    PublishMapTaskOutputMsg(
        smid(2), I32_MAX, I32_MAX, 4096, first_reduce_id=0,
        last_reduce_id=4095, entries=_big_entries(4096), epoch=I32_MAX,
    ),
    # FetchMapStatusMsg
    FetchMapStatusMsg(smid(3), smid(4), 0, 0, block_ids=[]),
    FetchMapStatusMsg(
        smid(3), smid(4), I32_MAX, I32_MAX,
        block_ids=[(I32_MAX, I32_MIN)],
    ),
    FetchMapStatusMsg(
        smid(3), smid(4), 1, 2,
        block_ids=[(m, r) for m in range(64) for r in range(64)],
    ),
    # FetchMapStatusResponseMsg
    FetchMapStatusResponseMsg(0, 0, 0, locations=[]),
    FetchMapStatusResponseMsg(
        I32_MAX, 1, 0,
        locations=[BlockLocation(2**63 - 1, I32_MAX, I32_MAX)],
    ),
    FetchMapStatusResponseMsg(
        7, 5000, 0, locations=[loc(i) for i in range(5000)]
    ),
    # FetchMapStatusFailedMsg
    FetchMapStatusFailedMsg(0, reason=""),
    FetchMapStatusFailedMsg(I32_MAX, reason="x" * 1024),  # at max_len
    FetchMapStatusFailedMsg(1, reason="shuffle 3 unregistered: hôte"),
    # HeartbeatMsg
    HeartbeatMsg(smid(5), seq=0, is_ack=False),
    HeartbeatMsg(smid(5), seq=I32_MAX, is_ack=True),
    # FetchExchangePlanMsg
    FetchExchangePlanMsg(smid(6), 0, 0, window=-1),
    FetchExchangePlanMsg(smid(6), I32_MAX, I32_MAX, window=I32_MAX),
    # ExchangePlanMsg
    ExchangePlanMsg(0, [], [], []),
    ExchangePlanMsg(
        1, [smid(0)], [2**63 - 1], [((0, 0, 2**63 - 1),)],
        window=0, final=False, my_maps=(0,),
    ),
    ExchangePlanMsg(
        I32_MAX,
        [smid(i) for i in range(3)],
        list(range(9)),
        [
            tuple((m, r, (m + r) * 1024) for m in range(4) for r in range(4)),
            (),
            ((I32_MAX, I32_MIN, -1),),
        ],
        window=I32_MAX, final=True, my_maps=tuple(range(128)),
    ),
    # PublishShuffleMetricsMsg
    PublishShuffleMetricsMsg(smid(7), 0, payload=b""),
    PublishShuffleMetricsMsg(smid(7), 1, payload=b"\x00\xff" * 65536),
    FetchMapStatusMsg(
        smid(3), smid(4), 1, 2, block_ids=[(0, 1)],
        trace_id=2**64 - 1, span_id=1,
    ),
    # PrefetchHintMsg
    PrefetchHintMsg(0, locations=[]),
    PrefetchHintMsg(I32_MAX, locations=[loc(i) for i in range(2048)]),
    PrefetchHintMsg(
        9, locations=[loc(0)], trace_id=1, span_id=2**64 - 1,
    ),
    # CleanShuffleMsg
    CleanShuffleMsg(0),
    CleanShuffleMsg(I32_MAX),
    # PushSubBlockMsg (push-based merged shuffle, wire v3)
    PushSubBlockMsg(smid(8), 0, 0, 0, total_len=0, offset=0, data=b""),
    PushSubBlockMsg(
        smid(8), I32_MAX, I32_MAX, I32_MAX,
        total_len=I32_MAX, offset=I32_MAX - 7, data=b"\xff" * 7,
    ),
    PushSubBlockMsg(
        smid(8), 1, 2, 3, total_len=1 << 20, offset=4096,
        data=bytes(range(256)) * 64,
    ),
    # FetchMergeStatusMsg
    FetchMergeStatusMsg(smid(9), 0, 0, reduce_ids=()),
    FetchMergeStatusMsg(smid(9), I32_MAX, I32_MAX, reduce_ids=(I32_MAX,)),
    FetchMergeStatusMsg(smid(9), 1, 2, reduce_ids=tuple(range(4096))),
    # MergeStatusResponseMsg
    MergeStatusResponseMsg(0, 0, 0, 0, 0, 0, provenance=()),
    MergeStatusResponseMsg(
        I32_MAX, I32_MAX, I32_MAX, I32_MAX, I32_MAX, 2**63 - 1,
        provenance=((I32_MAX, 2**63 - 1, -1),),
    ),
    MergeStatusResponseMsg(
        7, 3, 1, 5, 42, 64 * 4096,
        provenance=tuple((m, m * 4096, 4096) for m in range(64)),
    ),
    # a re-assembly fragment: rows_total > len(provenance)
    MergeStatusResponseMsg(
        7, 3, 1, 5, 42, 64 * 4096,
        provenance=((0, 0, 4096),), rows_total=64,
    ),
]


@pytest.mark.parametrize(
    "msg", CASES, ids=[f"{type(m).__name__}-{i}" for i, m in enumerate(CASES)]
)
def test_roundtrip(msg):
    frame = msg.encode()
    out = decode_msg(frame)
    assert type(out) is type(msg)
    assert out == msg
    # decode is also a fixed point of encode
    assert out.encode() == frame


def test_roundtrip_cases_cover_every_message_type():
    covered = {type(m).MSG_TYPE for m in CASES}
    assert covered == set(MSG_TYPES)


# -- v2 trace tails: zero ids are invisible, v1 encoding drops them -----------

def _traced(cls_case: int):
    if cls_case == 0:
        return (
            FetchMapStatusMsg(smid(3), smid(4), 1, 2, block_ids=[(0, 1)]),
            FetchMapStatusMsg(
                smid(3), smid(4), 1, 2, block_ids=[(0, 1)],
                trace_id=0xABC, span_id=0xDEF,
            ),
        )
    return (
        PrefetchHintMsg(5, locations=[loc(0), loc(1)]),
        PrefetchHintMsg(
            5, locations=[loc(0), loc(1)], trace_id=0xABC, span_id=0xDEF,
        ),
    )


@pytest.mark.parametrize("case", [0, 1], ids=["fetch_map_status", "prefetch"])
def test_zero_trace_ids_encode_byte_identical_to_v1(case):
    """A trace-off run (all-default ids) must be bit-identical to wire
    v1 at EVERY encoding version — the invariant that keeps the
    pre-tail golden corpus green and the trace-off A/B honest."""
    plain, _ = _traced(case)
    base = plain.encode()
    assert plain.encode(wire_version=1) == base
    assert plain.encode(wire_version=2) == base


@pytest.mark.parametrize("case", [0, 1], ids=["fetch_map_status", "prefetch"])
def test_nonzero_trace_ids_suppressed_at_v1_carried_at_v2(case):
    """Nonzero ids add exactly the two tail fields at v2 and vanish —
    same bytes as the untraced message — when the peer negotiated v1."""
    plain, traced = _traced(case)
    v2 = traced.encode()
    assert len(v2) == len(plain.encode()) + 16
    out = decode_msg(v2)
    assert (out.trace_id, out.span_id) == (0xABC, 0xDEF)
    # pinned at the v1 peer's generation: tail suppressed, ids lost
    v1 = traced.encode(wire_version=1)
    assert v1 == plain.encode()
    out1 = decode_msg(v1)
    assert (out1.trace_id, out1.span_id) == (0, 0)


def test_trace_ids_survive_segmentation():
    """Every split part re-carries the parent's trace ids, so a
    re-assembled multi-segment status keeps its correlation."""
    msg = FetchMapStatusMsg(
        smid(3), smid(4), 1, 2,
        block_ids=[(m, r) for m in range(64) for r in range(8)],
        trace_id=0x77, span_id=0x88,
    )
    segs = msg.encode_segments(512)
    assert len(segs) > 1
    for seg in segs:
        part = decode_msg(bytes(seg))
        assert (part.trace_id, part.span_id) == (0x77, 0x88)
    # and v1 segmentation suppresses them on every part
    for seg in msg.encode_segments(512, wire_version=1):
        part = decode_msg(bytes(seg))
        assert (part.trace_id, part.span_id) == (0, 0)


def test_overlong_reason_truncates_to_max_len():
    msg = FetchMapStatusFailedMsg(9, reason="y" * 5000)
    out = decode_msg(msg.encode())
    assert out.reason == "y" * 1024


def test_location_entry_size_is_wire_constant():
    buf = bytearray()
    loc(0).write(buf)
    assert len(buf) == LOCATION_ENTRY_SIZE == 16
