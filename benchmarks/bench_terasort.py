#!/usr/bin/env python
"""BASELINE config 3: TeraSort (sortByKey) on the device mesh — plus
the OUT-OF-CORE tier bench (``--out-of-core``).

The reference's headline: HiBench TeraSort 175 GB over 100 GbE RoCE
(README.md:7-19).  The default mode is the same measurement as the
repo-root ``bench.py`` but parameterizable: sample → range-partition →
all_to_all → merge as ONE XLA program, reported as sorted bytes per
second per chip vs the reference's 12.5 GB/s NIC line rate.

``--out-of-core`` instead measures the tiered block store
(memory/tier.py) on a record-plane sort whose dataset exceeds the hot
budget: dataset sizes {1x, 4x, 8x} of ``tierHotBytes`` × prefetch
{on, off}, every map output committed file-backed (O_DIRECT data
files, cache-cold reads), sorted reduce over loopback.  Emits
``BENCH_out_of_core.json`` with per-config wall clock, a sampled
peak of every executor's resident hot bytes (the budget-bounding
census), peak process RSS, and the tier counter deltas embedded.

    python benchmarks/bench_terasort.py [log2_records]
    python benchmarks/bench_terasort.py --out-of-core
    BENCH_SMOKE=1 python benchmarks/bench_terasort.py --out-of-core
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (
    ROCE_LINE_RATE_GBPS,
    emit,
    maybe_spoof_cpu,
    time_iters,
    write_bench_json,
    zipf_keys,
)

SMOKE = bool(os.environ.get("BENCH_SMOKE"))

# tier counters whose per-config deltas the out-of-core sweep records
_TIER_COUNTERS = (
    "tier_hits_total", "tier_misses_total",
    "tier_promotes_total", "tier_promote_bytes_total",
    "tier_demotes_total", "tier_demote_bytes_total",
    "tier_evict_refusals_total", "tier_cold_read_bytes_total",
    "tier_prefetch_tasks_total", "tier_prefetch_useful_total",
    "tier_hint_msgs_total", "tier_hint_blocks_total",
    "tier_commit_bytes_total", "tier_bytes_never_read_total",
)


def _rss_kib() -> int:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss


def _ooc_cluster(base_port: int, hot_bytes: int, prefetch: bool):
    """Driver + 2 executors on loopback, every commit file-backed
    through the tier."""
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.transport import LoopbackNetwork

    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "120s",
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.fileBackedCommitBytes": 1,
        "spark.shuffle.tpu.tierHotBytes": hot_bytes,
        "spark.shuffle.tpu.tierPrefetch": prefetch,
        "spark.shuffle.tpu.metrics": True,
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 20 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    return net, driver, executors


def _ooc_run_once(base_port: int, hot_bytes: int, prefetch: bool,
                  keys: np.ndarray, vals: np.ndarray,
                  num_maps: int, num_parts: int):
    """One config: write the maps file-backed (untimed), then time the
    full sorted reduce of every partition while sampling each
    executor's resident hot bytes.  Returns the per-config record."""
    import threading
    from collections import defaultdict

    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.utils.columns import ColumnBatch

    c0 = {n: GLOBAL_REGISTRY.counter(n).value for n in _TIER_COUNTERS}
    net, driver, executors, = _ooc_cluster(base_port, hot_bytes, prefetch)
    maps_by_host = defaultdict(list)
    try:
        handle = driver.register_shuffle(
            1, num_maps, HashPartitioner(num_parts), key_ordering=True
        )
        n = len(keys) // num_maps
        written = 0
        for m in range(num_maps):
            ex = executors[m % 2]
            w = ex.get_writer(handle, m)
            w.write(ColumnBatch(keys[m * n:(m + 1) * n],
                                vals[m * n:(m + 1) * n]))
            w.stop(True)
            written += w.metrics.bytes_written
            maps_by_host[ex.local_smid].append(m)
        peak_hot = [0]
        stop = threading.Event()

        def sample():
            while not stop.is_set():
                peak_hot[0] = max(
                    peak_hot[0],
                    max(e.tier_store.stats()["hot_bytes"]
                        for e in executors),
                )
                time.sleep(0.002)

        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()
        t0 = time.perf_counter()
        records = 0
        key_sum = 0
        for pid in range(num_parts):
            reader = executors[pid % 2].get_reader(
                handle, pid, pid + 1, dict(maps_by_host)
            )
            for k, _v in reader.read():
                records += 1
                key_sum += int(k)
        wall = time.perf_counter() - t0
        stop.set()
        sampler.join(timeout=5)
        driver.unregister_shuffle(1)
        deltas = {
            n: GLOBAL_REGISTRY.counter(n).value - c0[n]
            for n in _TIER_COUNTERS
        }
        return {
            "prefetch": prefetch,
            "wall_s": round(wall, 4),
            "read_mb_s": round(written / wall / 1e6, 2),
            "written_bytes": written,
            "records": records,
            "key_sum": key_sum,
            "peak_hot_bytes": peak_hot[0],
            "hot_budget": hot_bytes,
            "hot_bounded": peak_hot[0] <= hot_bytes,
            "rss_kib": _rss_kib(),
            "tier": deltas,
        }
    finally:
        for m in executors + [driver]:
            m.stop()


def out_of_core_main():
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    GLOBAL_REGISTRY.enabled = True
    hot = (4 << 20) if SMOKE else (32 << 20)
    multiples = (1, 4) if SMOKE else (1, 4, 8)
    num_maps, num_parts = 4, 8
    payload = 128
    rec_bytes = 8 + payload
    rng = np.random.default_rng(42)
    results = {}
    port = 27800
    # untimed warmup: first-run import/serializer/connect costs must
    # not land on the first timed config (decode-sweep precedent)
    wk = rng.permutation((1 << 20) // rec_bytes).astype(np.int64)
    wv = np.frombuffer(
        rng.bytes(len(wk) * payload), dtype=f"S{payload}"
    )
    _ooc_run_once(port, hot, False, wk, wv, num_maps, num_parts)
    port += 40
    for mult in multiples:
        dataset = mult * hot
        n_rec = dataset // rec_bytes
        keys = rng.permutation(n_rec).astype(np.int64)
        vals = np.frombuffer(
            rng.bytes(n_rec * payload), dtype=f"S{payload}"
        )
        per_mult = {}
        for prefetch in (True, False):
            rec = _ooc_run_once(
                port, hot, prefetch, keys, vals, num_maps, num_parts
            )
            port += 40
            per_mult["on" if prefetch else "off"] = rec
            emit(
                f"out-of-core sorted reduce, dataset={mult}x hot "
                f"budget, prefetch={'on' if prefetch else 'off'}",
                rec["read_mb_s"] / 1000.0, "GB/s",
                rec["read_mb_s"] / 1000.0 / ROCE_LINE_RATE_GBPS,
            )
        on, off = per_mult["on"], per_mult["off"]
        assert on["records"] == off["records"] and \
            on["key_sum"] == off["key_sum"], \
            f"prefetch on/off outputs diverged at {mult}x"
        ratio = off["wall_s"] / on["wall_s"]
        per_mult["prefetch_speedup"] = round(ratio, 3)
        emit(
            f"prefetch-on speedup over prefetch-off at dataset={mult}x",
            ratio, "x", ratio / 1.25,  # the >=1.25x acceptance line
        )
        bounded = on["hot_bounded"] and off["hot_bounded"]
        emit(
            f"peak resident hot bytes within budget at {mult}x "
            f"(budget {hot}B)",
            max(on["peak_hot_bytes"], off["peak_hot_bytes"]),
            "bytes", 1.0 if bounded else 0.0,
        )
        results[f"{mult}x"] = per_mult
    host_note = None
    if (os.cpu_count() or 1) == 1:
        host_note = (
            "1-core bench container: warm work can only timeslice "
            "against the serves and decode it is meant to overlap, and "
            "this host's virtualized disk serves 'cold' reads from the "
            "hypervisor cache (mmap faults ~0.9 GB/s vs O_DIRECT "
            "~0.1 GB/s measured) — so prefetch pays its promotion copy "
            "with nothing to hide.  The >=1.25x criterion needs >=2 "
            "cores + genuinely cold storage; ratios recorded verbatim "
            "(PR 5 precedent), and conf tierPrefetch defaults OFF on "
            "single-core hosts for exactly this reason."
        )
    write_bench_json(
        "out_of_core",
        extra={
            "tier_hot_bytes": hot,
            "num_maps": num_maps,
            "num_partitions": num_parts,
            "record_bytes": rec_bytes,
            "host_cores": os.cpu_count(),
            "host_note": host_note,
            "configs": results,
        },
        out_dir="/tmp" if SMOKE else None,
    )


def main():
    import jax

    from sparkrdma_tpu.models.terasort import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    maybe_spoof_cpu()
    zipf = "--zipf" in sys.argv
    argv = [a for a in sys.argv[1:] if not a.startswith("--")]
    log2 = int(argv[0]) if argv else 24
    n = 1 << log2
    mesh = make_mesh()
    sorter = TeraSorter(mesh)
    rng = np.random.default_rng(42)
    if zipf:
        # Zipfian key column (rank-preserving, s=1.5): the sampled
        # range partition has to cope with a head that dwarfs the
        # median — the device-plane face of the skew/ subsystem's
        # workload
        host_keys = zipf_keys(rng, 1.5, n, 1 << 20, dtype=np.int32)
    else:
        host_keys = rng.integers(0, 1 << 31, n, dtype=np.int32)
    keys = jax.device_put(host_keys, sorter.sharding)
    vals = jax.device_put(
        rng.integers(0, 1 << 31, n, dtype=np.int32), sorter.sharding
    )

    def run():
        (sk, sv, n_valid, _), _cap = sorter.sort_device(keys, vals)
        return sk, n_valid

    dt = time_iters(run, iters=20)
    n_chips = len(list(mesh.devices.flat))
    gbps_chip = n * 8 / dt / 1e9 / n_chips
    label = "zipf s=1.5 keys" if zipf else "uniform keys"
    emit(
        f"terasort shuffle+sort throughput per chip ({n} records, "
        f"{label}, {n_chips} chip(s))",
        gbps_chip, "GB/s/chip", gbps_chip / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    if "--out-of-core" in sys.argv:
        import jax

        # record-plane bench: no device mesh needed, and a wedged
        # tunnel grant must not hang backend init (the maybe_spoof_cpu
        # rationale, unconditionally — this mode never touches a chip)
        jax.config.update("jax_platforms", "cpu")
        out_of_core_main()
    else:
        main()
