#!/usr/bin/env python
"""BASELINE config 3: TeraSort (sortByKey) on the device mesh.

The reference's headline: HiBench TeraSort 175 GB over 100 GbE RoCE
(README.md:7-19).  This is the same measurement as the repo-root
``bench.py`` but parameterizable: sample → range-partition →
all_to_all → merge as ONE XLA program, reported as sorted bytes per
second per chip vs the reference's 12.5 GB/s NIC line rate.

    python benchmarks/bench_terasort.py [log2_records]
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import ROCE_LINE_RATE_GBPS, emit, maybe_spoof_cpu, time_iters

from sparkrdma_tpu.models.terasort import TeraSorter
from sparkrdma_tpu.parallel.mesh import make_mesh


def main():
    maybe_spoof_cpu()
    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    n = 1 << log2
    mesh = make_mesh()
    sorter = TeraSorter(mesh)
    rng = np.random.default_rng(42)
    keys = jax.device_put(
        rng.integers(0, 1 << 31, n, dtype=np.int32), sorter.sharding
    )
    vals = jax.device_put(
        rng.integers(0, 1 << 31, n, dtype=np.int32), sorter.sharding
    )

    def run():
        (sk, sv, n_valid, _), _cap = sorter.sort_device(keys, vals)
        return sk, n_valid

    dt = time_iters(run, iters=20)
    n_chips = len(list(mesh.devices.flat))
    gbps_chip = n * 8 / dt / 1e9 / n_chips
    emit(
        f"terasort shuffle+sort throughput per chip ({n} records, "
        f"{n_chips} chip(s))",
        gbps_chip, "GB/s/chip", gbps_chip / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
