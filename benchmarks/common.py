"""Shared helpers for the benchmark suite.

Every benchmark prints one JSON line per metric, the same shape as the
repo-root ``bench.py``:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares against the reference data plane's per-node
ceiling — the 100 GbE RoCE line rate of 12.5 GB/s that bounds
SparkRDMA's shuffle throughput (reference README.md:7-19) — unless a
benchmark states its own baseline.

Every emitted record is also collected in-process so
:func:`write_bench_json` can write a ``BENCH_<name>.json`` embedding
the results TOGETHER with a metrics-registry snapshot
(sparkrdma_tpu/metrics/) — a bench run carries its own transport /
shuffle / memory counters for later attribution
(``tools/metrics_report.py`` renders the embedded snapshot).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

import jax
import numpy as np

# 100 GbE RoCE line rate, the reference's per-node data-plane ceiling (GB/s)
ROCE_LINE_RATE_GBPS = 12.5

# every emit() record of this process, in order
RESULTS: list = []


def fence(x) -> None:
    """Trustworthy device fence: fetch a TINY slice of the last
    dispatched output.  Device execution is in-order, so this fences
    every prior dispatch too; plain block_until_ready can return early
    on the tunneled single-chip platform, and fetching the full array
    would drag megabytes through the tunnel into the timing."""
    if hasattr(x, "ravel") and getattr(x, "size", 1) > 1:
        x = x.ravel()[-1:]
    np.asarray(jax.device_get(x))


def time_iters(run: Callable[[], object], iters: int, warmup: int = 2) -> float:
    """Mean seconds per iteration; dispatches asynchronously and fences
    once so the host round trip is amortized out."""
    out = None
    for _ in range(warmup):
        out = run()
    fence(jax.tree.leaves(out)[-1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run()
    fence(jax.tree.leaves(out)[-1])
    return (time.perf_counter() - t0) / iters


def emit(metric: str, value: float, unit: str, vs_baseline: float) -> None:
    rec = {
        "metric": metric,
        "value": round(float(value), 3),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 3),
    }
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def enable_metrics(conf) -> None:
    """Turn the metrics registry on for a bench's TpuShuffleConf (and
    the process-wide registry, so transport/memory instruments created
    before the manager exist too)."""
    from sparkrdma_tpu.metrics import get_registry

    conf.set("metrics", True)
    get_registry().enabled = True


def metrics_snapshot() -> dict:
    """Point-in-time snapshot of the process-wide metrics registry."""
    from sparkrdma_tpu.metrics import get_registry

    return get_registry().snapshot()


def write_bench_json(name: str, extra: Optional[dict] = None,
                     out_dir: Optional[str] = None) -> str:
    """Write ``BENCH_<name>.json`` embedding every emitted result plus
    the current metrics snapshot; returns the path."""
    doc = {
        "bench": name,
        "results": list(RESULTS),
        "metrics": metrics_snapshot(),
    }
    if extra:
        doc.update(extra)
    base = out_dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    path = os.path.join(base, f"BENCH_{name}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {path}", flush=True)
    return path


# -- spoofed-mesh scaffolding for multi-device record-plane benches ---------

SPOOF_ENV = "SPARKRDMA_TPU_BENCH_SPOOFED"


def maybe_spoof_cpu() -> None:
    """When the spoof env is set, force the CPU platform BEFORE any
    backend init: the axon sitecustomize overrides a JAX_PLATFORMS env
    var, and a wedged tunnel grant hangs init forever — single-chip
    benches call this first so they can be gauged off-silicon."""
    import os

    if os.environ.get(SPOOF_ENV):
        import jax

        jax.config.update("jax_platforms", "cpu")


def ensure_multidevice(script_path: str, min_devices: int = 4) -> None:
    """Benches that need a multi-device mesh call this FIRST: on the
    single-chip bench host it re-execs the script onto a spoofed
    8-device CPU mesh (the same harness the test suite and the
    driver's dryrun use) and exits with the child's status."""
    import os
    import subprocess
    import sys

    import jax as _jax

    maybe_spoof_cpu()
    if len(_jax.devices()) >= min_devices:
        return
    if os.environ.get(SPOOF_ENV):
        raise RuntimeError(
            f"spoofed respawn still has <{min_devices} devices"
        )
    env = dict(os.environ)
    env[SPOOF_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.exit(subprocess.call(
        [sys.executable, os.path.abspath(script_path)], env=env
    ))


def zipf_keys(rng, s: float, n: int, n_keys: int = 100_000,
              dtype=np.int64) -> np.ndarray:
    """Rank-preserving bounded Zipf sample: key id == frequency rank
    (key 0 is the hottest).  Draws via inverse CDF over ranks
    1..n_keys, so P(key=r) ∝ 1/(r+1)^s exactly.

    This replaces the old ``rng.zipf(s, n) % n_keys`` idiom, which
    folds the unbounded tail onto arbitrary residues: the fold lands
    huge rank samples on top of small key ids at random, flattening
    the head and breaking the rank-frequency law the benchmark means
    to model."""
    ranks = np.arange(1, n_keys + 1, dtype=np.float64)
    cdf = np.cumsum(ranks ** -s)
    cdf /= cdf[-1]
    return np.searchsorted(cdf, rng.random(n)).astype(dtype)


def canonical_record_workload(n_records: int = 1_000_000, payload: int = 64,
                              n_keys: int = 512, seed: int = 0):
    """The shared record-plane workload (keys, S-payload vals) so the
    cross-plane BASELINE comparison benchmarks identical data."""
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, n_records).astype(np.int64)
    vals = np.frombuffer(
        rng.bytes(n_records * payload), dtype=f"S{payload}"
    )
    return keys, vals


def time_group_by_key(ctx, keys, vals, n_keys: int, reps: int = 3) -> float:
    """Warm + verify + best-of-reps seconds for a groupByKey of the
    canonical workload through a context."""
    ds = ctx.parallelize_columns(keys, vals, num_slices=8)
    out = ds.group_by_key(num_partitions=8).collect()
    assert len(out) == n_keys, f"expected {n_keys} groups, got {len(out)}"
    assert sum(len(vs) for _, vs in out) == len(keys)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ds.group_by_key(num_partitions=8).collect()
        best = min(best, time.perf_counter() - t0)
    return best
