"""Shared helpers for the benchmark suite.

Every benchmark prints one JSON line per metric, the same shape as the
repo-root ``bench.py``:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares against the reference data plane's per-node
ceiling — the 100 GbE RoCE line rate of 12.5 GB/s that bounds
SparkRDMA's shuffle throughput (reference README.md:7-19) — unless a
benchmark states its own baseline.
"""

from __future__ import annotations

import json
import time
from typing import Callable

import jax
import numpy as np

# 100 GbE RoCE line rate, the reference's per-node data-plane ceiling (GB/s)
ROCE_LINE_RATE_GBPS = 12.5


def fence(x) -> None:
    """Trustworthy device fence: fetch a TINY slice of the last
    dispatched output.  Device execution is in-order, so this fences
    every prior dispatch too; plain block_until_ready can return early
    on the tunneled single-chip platform, and fetching the full array
    would drag megabytes through the tunnel into the timing."""
    if hasattr(x, "ravel") and getattr(x, "size", 1) > 1:
        x = x.ravel()[-1:]
    np.asarray(jax.device_get(x))


def time_iters(run: Callable[[], object], iters: int, warmup: int = 2) -> float:
    """Mean seconds per iteration; dispatches asynchronously and fences
    once so the host round trip is amortized out."""
    out = None
    for _ in range(warmup):
        out = run()
    fence(jax.tree.leaves(out)[-1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run()
    fence(jax.tree.leaves(out)[-1])
    return (time.perf_counter() - t0) / iters


def emit(metric: str, value: float, unit: str, vs_baseline: float) -> None:
    print(json.dumps({
        "metric": metric,
        "value": round(float(value), 3),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 3),
    }), flush=True)
