#!/usr/bin/env python
"""BASELINE config 5: TPC-DS-style broadcast + exchange joins.

The reference's final configs are Spark SQL TPC-DS q64/q72 — star-schema
joins whose physical plans mix broadcast joins (small dimension) and
exchange shuffles (large×large).  Device-native equivalents:

- exchange join: both sides hash-partitioned + all_to_all, local
  sorted probe (models/join.py HashJoiner),
- broadcast join: dimension replicated, no exchange (BroadcastJoiner).

Reported as fact-side join throughput (rows/s and GB/s per chip).
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import ROCE_LINE_RATE_GBPS, emit, maybe_spoof_cpu, time_iters

from sparkrdma_tpu.models.join import (
    make_broadcast_join_step,
    make_hash_join_step,
)
from sparkrdma_tpu.models.join import HashJoiner, BroadcastJoiner
from sparkrdma_tpu.parallel.mesh import make_mesh


def main():
    maybe_spoof_cpu()
    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 22
    n_fact = 1 << log2
    n_dim = 1 << max(10, log2 - 6)
    mesh = make_mesh()
    rng = np.random.default_rng(11)

    dim_keys = np.arange(n_dim, dtype=np.int32)
    dim_vals = rng.integers(0, 1 << 31, n_dim, dtype=np.int32)
    fact_keys = rng.integers(0, n_dim, n_fact, dtype=np.int32)
    fact_vals = rng.integers(0, 1 << 31, n_fact, dtype=np.int32)

    for name, joiner in (
        ("exchange hash join", HashJoiner(mesh, capacity_factor=2.0)),
        ("broadcast join", BroadcastJoiner(mesh)),
    ):
        D = joiner.n_devices
        sh = joiner.sharding
        lk = jax.device_put(fact_keys, sh)
        lv = jax.device_put(fact_vals, sh)
        l_valid = jax.device_put(np.ones(n_fact, np.int32), sh)
        if isinstance(joiner, HashJoiner):
            cap = joiner._capacity((n_fact + n_dim) // D, 2.0)
            step = make_hash_join_step(
                mesh, n_fact // D, max(1, n_dim // D), cap
            )
            rk = jax.device_put(dim_keys, sh)
            rv = jax.device_put(dim_vals, sh)
            r_valid = jax.device_put(np.ones(n_dim, np.int32), sh)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            step = make_broadcast_join_step(mesh, n_fact // D, n_dim)
            rep = NamedSharding(mesh, P(None))
            rk = jax.device_put(dim_keys, rep)
            rv = jax.device_put(dim_vals, rep)
            r_valid = jax.device_put(np.ones(n_dim, np.int32), rep)

        def run():
            out = step(lk, lv, l_valid, rk, rv, r_valid)
            return out[0], out[3]

        dt = time_iters(run, iters=10)
        gbps_chip = n_fact * 8 / dt / 1e9 / D
        emit(
            f"{name} fact-side throughput per chip ({n_fact} rows, "
            f"{D} chip(s))",
            gbps_chip, "GB/s/chip", gbps_chip / ROCE_LINE_RATE_GBPS,
        )


if __name__ == "__main__":
    main()
