#!/usr/bin/env python
"""Device-native exchange: the TPU/XLA collective plane vs the
host-staged tile loop vs the socket pull reader.

Three tiers on one forced >=2-device CPU mesh (re-exec harness shared
with the other multi-device benches; on real silicon the mesh is the
TPU slice):

1. raw exchange plane — identical padded payloads through
   ``TileExchange.exchange_padded`` (full-shot AND windowed rounds)
   and ``exchange_into`` (host [D, D, tile] staging matrices per
   round): the tentpole's per-call H2D/collective win.
2. bucketized exchange (the headline) — one shared hash-bucketize of
   int32 (key, val) records produces the REAL skewed per-pair lengths,
   then the bucketized payload moves device-native
   (``exchange_padded``) vs host-staged (``exchange_into``): the
   committed artifact records the device path >= 1.3x.  The fully
   fused on-device bucketize+all_to_all program
   (``ops.exchange.hash_exchange``, ``deviceBucketizeEnabled``) is
   emitted as a gauge alongside — on the spoofed CPU mesh it is
   XLA-CPU-sort-bound and NOT representative of TPU silicon, so it
   carries its own metric and never the headline.
3. socket comparison — one seeded loopback shuffle read end-to-end
   through readPlane=windowed with the device exchange ON vs OFF vs
   the readPlane=host socket pull reader.

``BENCH_device_exchange.json`` declares ``"min_devices": 2`` so the
bench gate skips these metrics on 1-device hosts instead of gating
garbage (tools/bench_gate.py).

Usage:
    python benchmarks/bench_device_exchange.py
    BENCH_SMOKE=1 python benchmarks/bench_device_exchange.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("BENCH_SMOKE"))

D = 2                                      # the CI mesh floor
PAIR_BYTES = (256 << 10) if SMOKE else (4 << 20)   # per (src, dst) pair
TILE_BYTES = (256 << 10) if SMOKE else (2 << 20)
REPS = 3 if SMOKE else 5
N_RECORDS = 100_000 if SMOKE else 1_000_000        # bucketized tier, per dev
NUM_MAPS, NUM_PARTS = (4, 4)
RECORDS_PER_MAP = 400 if SMOKE else 4000
REC_BYTES = 256


def _best(run, reps=REPS):
    run()  # warm (compile caches, pools)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def _bench_raw_plane(emit):
    import numpy as np

    from sparkrdma_tpu.parallel.exchange import (
        PaddedSourceRow,
        TileExchange,
        row_offsets,
    )
    from sparkrdma_tpu.parallel.mesh import make_mesh

    ex = TileExchange(make_mesh(D), tile_bytes=TILE_BYTES)
    rng = np.random.default_rng(0)
    lengths = np.full((D, D), PAIR_BYTES, np.int64)
    payload = int(lengths.sum())
    cols = ex.plan(lengths).total_cols
    contig, padded = {}, {}
    for s in range(D):
        offs = row_offsets(lengths[s])
        row = np.frombuffer(rng.bytes(int(offs[-1])), np.uint8).copy()
        contig[s] = row
        pad = np.zeros(D * cols, np.uint8)
        for d in range(D):
            pad[d * cols : d * cols + PAIR_BYTES] = row[
                int(offs[d]) : int(offs[d + 1])
            ]
        padded[s] = PaddedSourceRow(pad, cols)

    host_s = _best(lambda: ex.exchange_into(lengths, contig))
    dev_s = _best(lambda: ex.exchange_padded(lengths, padded))
    devw_s = _best(lambda: ex.exchange_padded(
        lengths, padded, window_rounds=2
    ))
    mb = payload / 1e6
    emit("raw exchange host-staged tile loop throughput "
         f"({D}x{D} x {PAIR_BYTES >> 10}KiB pairs)",
         mb / host_s, "MB/s", 1.0)
    emit("raw exchange device-native full-shot throughput "
         "(padded rows, donated program)",
         mb / dev_s, "MB/s", host_s / dev_s)
    emit("raw exchange device-native windowed-rounds throughput "
         "(window_rounds=2 overlap shape)",
         mb / devw_s, "MB/s", host_s / devw_s)
    emit("device-native vs host-staged speedup (raw exchange plane)",
         host_s / dev_s, "x", host_s / dev_s)


def _bench_bucketized(emit):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.ops.exchange import hash_exchange
    from sparkrdma_tpu.parallel.exchange import TileExchange, row_offsets
    from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh

    from sparkrdma_tpu.parallel.exchange import PaddedSourceRow

    mesh = make_mesh(D)
    n_local = N_RECORDS
    rng = np.random.default_rng(1)
    keys_h = rng.integers(0, 1 << 30, D * n_local).astype(np.int32)
    vals_h = rng.integers(0, 1 << 30, D * n_local).astype(np.int32)

    # shared map-side prep: hash-bucketize every source's (key, val)
    # records — the REAL skewed per-pair lengths both exchange shapes
    # then move (murmur3 finalizer, the hash_partition_ids analog)
    lengths = np.zeros((D, D), np.int64)
    buckets = []
    for s in range(D):
        k = keys_h[s * n_local : (s + 1) * n_local]
        v = vals_h[s * n_local : (s + 1) * n_local]
        x = k.astype(np.uint32)
        x = (x ^ (x >> 16)) * np.uint32(0x85EBCA6B)
        x = (x ^ (x >> 13)) * np.uint32(0xC2B2AE35)
        ids = (x ^ (x >> 16)) % np.uint32(D)
        order = np.argsort(ids, kind="stable")
        counts = np.bincount(ids, minlength=D)
        lengths[s] = counts * 8  # 4B key + 4B val per record
        buckets.append((k[order], v[order], counts))

    ex = TileExchange(mesh, tile_bytes=TILE_BYTES)
    cols = ex.plan(lengths).total_cols
    contig, padded = {}, {}
    for s in range(D):
        ks, vs, counts = buckets[s]
        offs = row_offsets(lengths[s])
        row = np.empty(int(offs[-1]), np.uint8)
        pad = np.zeros(D * cols, np.uint8)
        pos = 0
        for d in range(D):
            n = int(counts[d])
            seg = row[int(offs[d]) : int(offs[d + 1])]
            seg[: n * 4] = ks[pos : pos + n].view(np.uint8)
            seg[n * 4 :] = vs[pos : pos + n].view(np.uint8)
            pad[d * cols : d * cols + n * 8] = seg
            pos += n
        contig[s] = row
        padded[s] = PaddedSourceRow(pad, cols)

    host_s = _best(lambda: ex.exchange_into(lengths, contig))
    dev_s = _best(lambda: ex.exchange_padded(lengths, padded))
    moved = int(lengths.sum()) / 1e6
    emit("bucketized exchange host-staged throughput "
         f"(tile loop over bucketized columns, {D}x{N_RECORDS} "
         "records)",
         moved / host_s, "MB/s", 1.0)
    emit("bucketized exchange device-native throughput "
         "(exchange_padded over bucketized columns)",
         moved / dev_s, "MB/s", host_s / dev_s)
    emit("device-native vs host-staged speedup (bucketized exchange)",
         host_s / dev_s, "x", host_s / dev_s)

    # fully fused on-device bucketize + all_to_all gauge: ONE jitted
    # program (deviceBucketizeEnabled).  On the spoofed CPU mesh the
    # XLA sort dominates (single-core lax.sort), so this gauges the
    # program shape, never the headline — real TPU silicon is the
    # target for this number.
    conf = TpuShuffleConf()
    if not conf.device_bucketize_enabled:
        print("# deviceBucketizeEnabled off (1-device census) — "
              "fused gauge skipped", flush=True)
        return
    capacity = (2 * n_local) // D
    spec = P(EXCHANGE_AXIS)

    def body(k, v, m):
        ek, ev, em, max_fill = hash_exchange(k, v, m, D, capacity)
        return ek, ev, em, max_fill[None]

    fused = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec,) * 4,
    ))
    sharding = NamedSharding(mesh, spec)
    keys = jax.device_put(jnp.asarray(keys_h), sharding)
    vals = jax.device_put(jnp.asarray(vals_h), sharding)
    valid = jax.device_put(jnp.ones(D * n_local, jnp.int32), sharding)

    def run_fused():
        out = fused(keys, vals, valid)
        jax.block_until_ready(out)
        return out

    fused_s = _best(run_fused)
    emit("device-fused bucketize+all_to_all gauge "
         "(one jitted program; XLA-CPU-sort-bound on spoofed mesh)",
         moved / fused_s, "MB/s", host_s / fused_s)


def _bench_socket_cluster(emit):
    import threading

    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.bulk import (
        BulkShuffleSession,
        WindowedReadPlane,
    )
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import LoopbackNetwork

    base_ports = iter((47800, 48050, 48300))
    payload = NUM_MAPS * RECORDS_PER_MAP * REC_BYTES / 1e6
    planes = (
        ("socket pull reader (readPlane=host)",
         {"spark.shuffle.tpu.readPlane": "host"}),
        ("windowed host-staged exchange (deviceExchangeEnabled=false)",
         {"spark.shuffle.tpu.readPlane": "windowed",
          "spark.shuffle.tpu.deviceExchangeEnabled": "false"}),
        ("windowed device-native exchange (deviceExchangeEnabled=true)",
         {"spark.shuffle.tpu.readPlane": "windowed",
          "spark.shuffle.tpu.deviceExchangeEnabled": "true"}),
    )
    results = {}
    for label, extra in planes:
        base = next(base_ports)
        net = LoopbackNetwork()
        overrides = {
            "spark.shuffle.tpu.driverPort": base,
            "spark.shuffle.tpu.partitionLocationFetchTimeout": "15s",
            "spark.shuffle.tpu.bulkWindowMaps": "2",
        }
        overrides.update(extra)
        conf = TpuShuffleConf(overrides)
        driver = TpuShuffleManager(conf, is_driver=True, network=net)
        executors = [
            TpuShuffleManager(
                conf, is_driver=False, network=net,
                port=base + 100 + i * 10, executor_id=str(i),
                stage_to_device=False,
            )
            for i in range(D)
        ]
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if all(len(e._peers) == D for e in executors):
                    break
                time.sleep(0.01)
            if conf.read_plane == "windowed":
                session = BulkShuffleSession(
                    TileExchange.from_conf(conf, make_mesh(D)), D,
                    timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
                    window_rounds=conf.device_exchange_window_rounds,
                )
                for e in executors:
                    e.windowed_plane = WindowedReadPlane(
                        e, session=session
                    )
            rng = np.random.default_rng(7)
            part = HashPartitioner(NUM_PARTS)
            records = [
                [(f"m{m}k{j}", rng.bytes(REC_BYTES))
                 for j in range(RECORDS_PER_MAP)]
                for m in range(NUM_MAPS)
            ]
            def run_round(sid):
                handle = driver.register_shuffle(sid, NUM_MAPS, part)
                locs = {}
                for m, recs in enumerate(records):
                    e = executors[m % D]
                    w = e.get_writer(handle, m)
                    w.write(recs)
                    w.stop(True)
                    locs.setdefault(e.local_smid, []).append(m)
                got, errs = {}, {}

                def reduce_task(pid):
                    try:
                        r = executors[pid % D].get_reader(
                            handle, pid, pid + 1, dict(locs)
                        )
                        got[pid] = sum(1 for _ in r.read())
                    except BaseException as exc:
                        errs[pid] = exc

                ts = [
                    threading.Thread(target=reduce_task, args=(p,),
                                     daemon=True)
                    for p in range(NUM_PARTS)
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=120)
                assert not errs, errs
                total = sum(got.values())
                assert total == NUM_MAPS * RECORDS_PER_MAP, total
                return total

            sid_counter = iter(range(900, 960))
            run_round(next(sid_counter))  # warm
            best = float("inf")
            for _ in range(REPS):
                t0 = time.perf_counter()
                run_round(next(sid_counter))
                best = min(best, time.perf_counter() - t0)
            results[label] = best
        finally:
            for m in executors + [driver]:
                m.stop()
    base_s = results[planes[0][0]]
    for label, _ in planes:
        s = results[label]
        emit(f"end-to-end shuffle read throughput: {label} "
             f"({NUM_MAPS} maps x {RECORDS_PER_MAP} x {REC_BYTES}B)",
             payload / s, "MB/s", base_s / s)


def main():
    from benchmarks.common import (
        emit,
        ensure_multidevice,
        write_bench_json,
    )

    ensure_multidevice(__file__, min_devices=D)

    _bench_raw_plane(emit)
    _bench_bucketized(emit)
    _bench_socket_cluster(emit)
    write_bench_json(
        "device_exchange",
        extra={"min_devices": D, "smoke": SMOKE},
        out_dir="/tmp" if SMOKE else None,
    )


if __name__ == "__main__":
    main()
