#!/usr/bin/env python
"""Skewed-workload bench tier: hot-key sweep over the host shuffle
plane (ISSUE 14).

Workloads: rank-preserving bounded Zipf keys at s ∈ {1.1, 1.5} plus a
uniform control, each run with skew-adaptive splitting ON and OFF
(the OFF runs ARE the unsplit baseline, embedded in the output).
Each run is a fresh loopback cluster (driver + 2 executors), columnar
serializer, untimed map writes, then a timed sorted reduce of every
partition.  Per run the bench records wall clock, the skew registry's
commit accounting (partitions split / sub-blocks / split bytes), the
largest single block any fetch serves (markers excluded — on a split
map output that is the largest SUB-block), and the reader's merge
fan-in histogram delta.

On/off runs of the same workload must agree on record count and key
checksum — the bit-exactness line the test suite proves, re-checked
here on bench-sized data.

Emits ``BENCH_skew.json``.  Acceptance (ISSUE 14): s=1.5 split-on
wall ≥ 1.3x faster than split-off, or on a 1-core host (where serves
cannot overlap) the hot partition's fetch serialization measurably
broken up: max single-block serve ≤ skewSplitThreshold and merge
fan-in > 1, with the host note recorded.  Uniform with skew on stays
≥ 0.95x of off.

    BENCH_SMOKE=1 python benchmarks/bench_skew.py
"""

import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import emit, write_bench_json, zipf_keys

SMOKE = bool(os.environ.get("BENCH_SMOKE"))

NUM_MAPS = 4
NUM_PARTS = 8
PAYLOAD = 64
N_KEYS = 1000
THRESHOLD = "128k"
THRESHOLD_BYTES = 128 << 10


def _cluster(base_port: int, skew_on: bool):
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.transport import LoopbackNetwork

    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "120s",
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.skewEnabled": skew_on,
        "spark.shuffle.tpu.skewSplitThreshold": THRESHOLD,
        # full-size hot buckets need ~32 sub-blocks at the 128k
        # target; the default cap (16) would fold the tail into one
        # oversized final sub
        "spark.shuffle.tpu.skewMaxSubBlocks": 64,
        "spark.shuffle.tpu.metrics": True,
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 20 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    return net, driver, executors


def _max_serve_bytes(mto) -> int:
    """Largest single block a fetch of this map output can serve:
    every non-marker entry is served whole, so on a split output this
    is the largest SUB-block, not the hot partition's total."""
    from sparkrdma_tpu.skew import is_split_marker

    best = 0
    for r in range(mto.num_partitions):
        loc = mto.get_location(r)
        if loc.is_empty or is_split_marker(loc):
            continue
        best = max(best, loc.length)
    return best


def _run_once(base_port: int, shuffle_id: int, skew_on: bool,
              keys: np.ndarray, vals: np.ndarray):
    """One cluster, one shuffle: untimed chunked map writes, timed
    sorted reduce of all partitions.  Returns the per-run record."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.skew import get_skew
    from sparkrdma_tpu.utils.columns import ColumnBatch

    fanin = GLOBAL_REGISTRY.histogram("skew_merge_fanin")
    f_count0, f_sum0 = fanin.count, fanin.sum
    net, driver, executors = _cluster(base_port, skew_on)
    maps_by_host = defaultdict(list)
    max_serve = 0
    try:
        handle = driver.register_shuffle(
            shuffle_id, NUM_MAPS, HashPartitioner(NUM_PARTS),
            key_ordering=True,
        )
        n = len(keys) // NUM_MAPS
        written = 0
        chunk = 2048  # many serializer frames per bucket => splittable
        for m in range(NUM_MAPS):
            ex = executors[m % 2]
            w = ex.get_writer(handle, m)
            mk, mv = keys[m * n:(m + 1) * n], vals[m * n:(m + 1) * n]
            for a in range(0, len(mk), chunk):
                w.write(ColumnBatch(mk[a:a + chunk], mv[a:a + chunk]))
            mto = w.stop(True)
            written += w.metrics.bytes_written
            max_serve = max(max_serve, _max_serve_bytes(mto))
            maps_by_host[ex.local_smid].append(m)
        stats = dict(get_skew().shuffle_stats(shuffle_id))
        t0 = time.perf_counter()
        records = 0
        key_sum = 0
        for pid in range(NUM_PARTS):
            reader = executors[pid % 2].get_reader(
                handle, pid, pid + 1, dict(maps_by_host)
            )
            for k, _v in reader.read():
                records += 1
                key_sum += int(k)
        wall = time.perf_counter() - t0
        driver.unregister_shuffle(shuffle_id)
        return {
            "skew_enabled": skew_on,
            "wall_s": round(wall, 4),
            "read_mb_s": round(written / wall / 1e6, 2),
            "written_bytes": written,
            "records": records,
            "key_sum": key_sum,
            "max_serve_bytes": max_serve,
            "partitions_split": stats.get("partitions_split", 0),
            "sub_blocks": stats.get("sub_blocks", 0),
            "split_bytes": stats.get("split_bytes", 0),
            "merge_fanin_count": fanin.count - f_count0,
            "merge_fanin_sum": fanin.sum - f_sum0,
        }
    finally:
        for m in executors + [driver]:
            m.stop()


def main():
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.skew import get_skew

    GLOBAL_REGISTRY.enabled = True
    get_skew().reset()
    n_rec = 120_000 if SMOKE else 600_000
    rng = np.random.default_rng(14)
    vals = np.frombuffer(rng.bytes(n_rec * PAYLOAD), dtype=f"S{PAYLOAD}")
    workloads = {
        "zipf_s1.1": zipf_keys(rng, 1.1, n_rec, N_KEYS),
        "zipf_s1.5": zipf_keys(rng, 1.5, n_rec, N_KEYS),
        "uniform": rng.integers(0, N_KEYS, n_rec).astype(np.int64),
    }
    port = 28600
    # untimed warmup: first-run import/serializer/connect costs must
    # not land on the first timed config (decode-sweep precedent)
    _run_once(port, 99, True, workloads["zipf_s1.5"][:20_000],
              vals[:20_000])
    port += 40
    results = {}
    sid = 100
    for name, keys in workloads.items():
        per = {}
        for skew_on in (True, False):
            rec = _run_once(port, sid, skew_on, keys, vals)
            port += 40
            sid += 1
            per["on" if skew_on else "off"] = rec
            emit(
                f"sorted reduce, {name}, split="
                f"{'on' if skew_on else 'off'}",
                rec["read_mb_s"] / 1000.0, "GB/s", 1.0,
            )
        on, off = per["on"], per["off"]
        assert on["records"] == off["records"] and \
            on["key_sum"] == off["key_sum"], \
            f"split on/off outputs diverged on {name}"
        ratio = off["wall_s"] / on["wall_s"]
        per["split_speedup"] = round(ratio, 3)
        results[name] = per
        if name.startswith("zipf"):
            emit(
                f"split-on speedup over unsplit baseline, {name}",
                ratio, "x", ratio / 1.3,  # the >=1.3x acceptance line
            )
    hot = results["zipf_s1.5"]["on"]
    serial_broken = (
        hot["partitions_split"] >= 1
        and hot["max_serve_bytes"] <= THRESHOLD_BYTES
        and hot["merge_fanin_count"] > 0
        and hot["merge_fanin_sum"] > hot["merge_fanin_count"]
    )
    emit(
        "hot-partition fetch serialization broken up at zipf s=1.5 "
        f"(max single-block serve <= {THRESHOLD}, merge fan-in > 1)",
        hot["max_serve_bytes"], "bytes", 1.0 if serial_broken else 0.0,
    )
    uniform_ratio = results["uniform"]["split_speedup"]
    emit(
        "uniform control: skew-on wall vs skew-off",
        uniform_ratio, "x", uniform_ratio / 0.95,
    )
    host_note = None
    if (os.cpu_count() or 1) == 1:
        host_note = (
            "1-core bench container: the split sub-blocks of the hot "
            "partition can only timeslice — the balanced fetch plan "
            "has no second core to overlap serves on, so the >=1.3x "
            "wall-clock line is out of reach by construction (the "
            "decodeThreads/tierPrefetch precedent).  The structural "
            "claim is checked instead: the hot partition really is "
            "served as sub-blocks no larger than skewSplitThreshold "
            "and the reader really merges fan-in > 1; wall-clock "
            "ratios recorded verbatim."
        )
    write_bench_json(
        "skew",
        extra={
            "num_maps": NUM_MAPS,
            "num_partitions": NUM_PARTS,
            "records": n_rec,
            "payload_bytes": PAYLOAD,
            "n_keys": N_KEYS,
            "split_threshold": THRESHOLD,
            "host_cores": os.cpu_count(),
            "host_note": host_note,
            "unsplit_baseline": {
                name: per["off"] for name, per in results.items()
            },
            "workloads": results,
        },
        out_dir="/tmp" if SMOKE else None,
    )


if __name__ == "__main__":
    import jax

    # record-plane bench: never touches a chip; a wedged tunnel grant
    # must not hang backend init (bench_terasort --out-of-core idiom)
    jax.config.update("jax_platforms", "cpu")
    main()
