#!/usr/bin/env python
"""BASELINE config 5 end-to-end: a TPC-DS q64/q72-shaped pipeline.

q64/q72 physical plans chain exchange and broadcast joins over a
star-schema fact table and finish in an aggregation.  The skeleton here
does the same through the device models, chained ENTIRELY on device:

  stage 1: fact ⋈ dim1 (exchange hash join on fk1, payload carries fk2)
  stage 2: result ⋈ dim2 (broadcast join on fk2, payload carries dv1)
  stage 3: aggregateByKey over the surviving rows (sum/count/min/max)

No compaction between stages: each join's ``found`` mask IS the next
stage's validity column (unmatched rows ride along as ROLE_INVALID and
can never join or aggregate), so stage outputs stay device-resident
with static shapes and only a one-element fence touches the host —
the SQL-engine pattern of keeping exchanges on the fabric end to end.

Two variants are reported: the 3-stage pipeline above, and the fused
2-stage pipeline where stages 2+3 run as ONE sort
(models/join_aggregate.py — the group key here is a pure function of
the stage-2 join key, the fusion precondition).  Reported as fact-row
bytes through the full pipeline per second per chip.
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import ROCE_LINE_RATE_GBPS, emit, maybe_spoof_cpu, time_iters

from sparkrdma_tpu.models.aggregate import make_aggregate_step
from sparkrdma_tpu.models.join import (
    HashJoiner,
    make_broadcast_join_step,
    make_hash_join_step,
)
from sparkrdma_tpu.parallel.mesh import make_mesh


def main():
    maybe_spoof_cpu()
    import functools

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 22
    n_fact = 1 << log2
    n_dim1 = 1 << max(10, log2 - 6)
    n_dim2 = 1 << max(8, log2 - 8)
    mesh = make_mesh()
    rng = np.random.default_rng(21)

    # star schema: fact(fk1, fk2), dim1(k→v), dim2(k→v); ~93% of fact
    # rows survive stage 1 (dim1 keys cover most of fk1's range), the
    # broadcast stage keeps all survivors (dense dim2 keys)
    dim1_keys = np.sort(
        rng.choice(int(n_dim1 * 1.07), n_dim1, replace=False)
    ).astype(np.int32)
    dim1_vals = rng.integers(0, 1 << 31, n_dim1, dtype=np.int32)
    dim2_keys = np.arange(n_dim2, dtype=np.int32)
    dim2_vals = rng.integers(0, 1 << 31, n_dim2, dtype=np.int32)
    fk1 = rng.integers(0, int(n_dim1 * 1.07), n_fact).astype(np.int32)
    fk2 = rng.integers(0, n_dim2, n_fact).astype(np.int32)

    joiner = HashJoiner(mesh, capacity_factor=2.0)
    D = joiner.n_devices
    sh = joiner.sharding
    rep = NamedSharding(mesh, P(None))

    cap1 = joiner._capacity((n_fact + n_dim1) // D, 2.0)
    step1 = make_hash_join_step(mesh, n_fact // D, n_dim1 // D, cap1)
    m1 = (n_fact + n_dim1) if D == 1 else D * D * cap1
    step2 = make_broadcast_join_step(mesh, m1 // D, n_dim2)
    m2 = m1 + D * n_dim2
    cap3 = joiner._capacity(m2 // D, 2.0)
    step3 = make_aggregate_step(mesh, m2 // D, cap3)

    # group-key/value prep between stages 2 and 3, on device
    @functools.partial(
        jax.jit,
        in_shardings=(sh, sh, sh, sh),
        out_shardings=(sh, sh),
    )
    def prep3(sk2, spay2, fval2, found2):
        return (sk2 % jnp.uint32(1024), spay2 ^ fval2)

    lk = jax.device_put(fk1, sh)
    lv = jax.device_put(fk2, sh)
    l_valid = jax.device_put(np.ones(n_fact, np.int32), sh)
    rk1 = jax.device_put(dim1_keys, sh)
    rv1 = jax.device_put(dim1_vals, sh)
    r1_valid = jax.device_put(np.ones(n_dim1, np.int32), sh)
    rk2 = jax.device_put(dim2_keys, rep)
    rv2 = jax.device_put(dim2_vals, rep)
    r2_valid = jax.device_put(np.ones(n_dim2, np.int32), rep)

    def pipeline():
        sk1, spay1, fval1, found1, _isf1, fill1 = step1(
            lk, lv, l_valid, rk1, rv1, r1_valid
        )
        # stage 2: join key = the fk2 payload, value = dim1's value,
        # validity = stage 1's found mask (no compaction)
        sk2, spay2, fval2, found2, _isf2 = step2(
            spay1, fval1, found1, rk2, rv2, r2_valid
        )
        k3, v3 = prep3(sk2, spay2, fval2, found2)
        uniq, sums, counts, mins, maxs, n_unique, fill3 = step3(
            k3, v3, found2
        )
        return counts, fill1, fill3

    # sanity once: no bucket overflow, and the aggregate saw every
    # matched fact row (dim1 covers ~93% of fk1's key space)
    counts, fill1, fill3 = pipeline()
    assert int(np.max(np.asarray(fill1))) <= cap1, "stage-1 overflow"
    assert int(np.max(np.asarray(fill3))) <= cap3, "stage-3 overflow"
    total = int(np.asarray(counts).sum())
    assert total > 0.9 * n_fact, (total, n_fact)

    dt = time_iters(lambda: pipeline()[0], iters=5)
    gbps_chip = n_fact * 8 / dt / 1e9 / D
    emit(
        f"TPC-DS q64/q72-shaped 2-join+aggregate device pipeline per "
        f"chip ({n_fact} fact rows, {D} chip(s))",
        gbps_chip, "GB/s/chip", gbps_chip / ROCE_LINE_RATE_GBPS,
    )

    # fused variant: stages 2+3 in ONE sort (join_aggregate.py); the
    # group key (join key % 1024) is a pure function of the join key
    from sparkrdma_tpu.models.join_aggregate import (
        make_broadcast_join_aggregate_step,
    )

    def gk_fn(ku):
        return ku % jnp.asarray(1024, ku.dtype)

    def val_fn(ku, fact_pay_u, dim_val_u):
        return jax.lax.bitcast_convert_type(
            fact_pay_u ^ dim_val_u, jnp.int32
        )

    step23 = make_broadcast_join_aggregate_step(
        mesh, m1 // D, n_dim2, gk_fn, val_fn
    )

    def pipeline_fused():
        sk1, spay1, fval1, found1, _isf1, fill1 = step1(
            lk, lv, l_valid, rk1, rv1, r1_valid
        )
        gk, sums, counts, mins, maxs, _n = step23(
            spay1, fval1, found1, rk2, rv2, r2_valid
        )
        return counts, fill1

    counts_f, fill1_f = pipeline_fused()
    assert int(np.max(np.asarray(fill1_f))) <= cap1, "stage-1 overflow"
    total_f = int(np.asarray(counts_f).sum())
    assert total_f == total, (total_f, total)

    dt_f = time_iters(lambda: pipeline_fused()[0], iters=5)
    gbps_f = n_fact * 8 / dt_f / 1e9 / D
    emit(
        f"TPC-DS pipeline, fused join+aggregate (ONE sort for stages "
        f"2+3) per chip ({n_fact} fact rows, {D} chip(s))",
        gbps_f, "GB/s/chip", gbps_f / ROCE_LINE_RATE_GBPS,
    )

    # single-dispatch variant: the WHOLE pipeline traced as one XLA
    # program — no per-stage launch (each dispatch costs a tunnel
    # round trip on the remote chip) and XLA may fuse across the
    # stage-1 output → stage-2 input boundary
    @functools.partial(
        jax.jit,
        in_shardings=(sh, sh, sh, sh, sh, sh, rep, rep, rep),
    )
    def pipeline_one(lk, lv, l_valid, rk1, rv1, r1_valid,
                     rk2, rv2, r2_valid):
        sk1, spay1, fval1, found1, _isf1, fill1 = step1(
            lk, lv, l_valid, rk1, rv1, r1_valid
        )
        gk, sums, counts, mins, maxs, _n = step23(
            spay1, fval1, found1, rk2, rv2, r2_valid
        )
        return counts, fill1

    counts_1, fill1_1 = pipeline_one(
        lk, lv, l_valid, rk1, rv1, r1_valid, rk2, rv2, r2_valid
    )
    assert int(np.max(np.asarray(fill1_1))) <= cap1, "stage-1 overflow"
    assert int(np.asarray(counts_1).sum()) == total

    dt_1 = time_iters(
        lambda: pipeline_one(
            lk, lv, l_valid, rk1, rv1, r1_valid, rk2, rv2, r2_valid
        )[0],
        iters=5,
    )
    gbps_1 = n_fact * 8 / dt_1 / 1e9 / D
    emit(
        f"TPC-DS pipeline, single-dispatch (whole pipeline = ONE XLA "
        f"program) per chip ({n_fact} fact rows, {D} chip(s))",
        gbps_1, "GB/s/chip", gbps_1 / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
