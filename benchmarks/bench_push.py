#!/usr/bin/env python
"""Push-based merged shuffle bench tier (ISSUE 18): many maps × many
reduces over a real 2-process ``ProcessCluster``, push vs pull.

The workload is the paper's worst case for reducer-pull: 64 maps × 64
reduce partitions (SMOKE: 16×16) of deliberately SMALL per-(map,
reduce) blocks sized near ``shuffleReadBlockSize``, so the pull plan
cannot amortize — every remote block is roughly one grouped fetch RPC
and a reduce task issues one per remote map.  Push mode moves the
same bytes at commit and each reduce task fetches ONE merged
sequential span instead (local blocks ride the same merged span, so
its RPC count is flat in M).

Both modes run the identical generated dataset (terasort records,
deterministic per-map seed) on a fresh 2-executor process fleet; every
partition's order-independent digest must agree between modes — the
bit-exactness line the test suite proves, re-checked at bench scale.

Reported:

- reader data-RPC count per mode (the ``shuffle_fetch_rpcs_total``
  counter delta over the read phase, summed across executor
  processes) and the pull:push ratio — acceptance is ≥10×,
- read-phase wall clock per mode, nested under a ``min_cores: 2``
  cluster tier so 1-core hosts report but never gate the overlap
  number (``tools/bench_gate.py`` skips with a note).

Emits ``BENCH_push.json``.

    BENCH_SMOKE=1 python benchmarks/bench_push.py
"""

import os
import sys
import time

sys.path.insert(0, ".")
from benchmarks.common import emit, write_bench_json

SMOKE = bool(os.environ.get("BENCH_SMOKE"))

NUM_MAPS = 16 if SMOKE else 64
NUM_PARTS = 16 if SMOKE else 64
# terasort records are ~115 B pickled; size each per-(map, reduce)
# block just OVER the 16k read-block floor so pull degenerates to one
# RPC per block — the small-random-IO regime push exists to collapse
RECORDS_PER_MAP = 2600 if SMOKE else 9600
READ_BLOCK = "16k"

BASE_PORT = 23600
SHUFFLE_ID = 18


def _conf(push: bool) -> dict:
    pfx = "spark.shuffle.tpu."
    return {
        pfx + "metrics": True,
        pfx + "pushEnabled": push,
        pfx + "shuffleReadBlockSize": READ_BLOCK,
        pfx + "partitionLocationFetchTimeout": "120s",
        pfx + "connectTimeout": "15s",
    }


def _fetch_rpcs(cluster) -> dict:
    """{mode: count} of ``shuffle_fetch_rpcs_total`` summed across the
    executor processes (readers run there, not on the driver)."""
    out = {}
    for ex in cluster.executors:
        snap = ex.call("metrics", timeout=60.0).get("metrics") or {}
        for c in snap.get("counters", []):
            if c["name"] == "shuffle_fetch_rpcs_total":
                mode = c["labels"].get("mode", "?")
                out[mode] = out.get(mode, 0) + c["value"]
    return out


def _run_mode(push: bool, base_port: int):
    """One full write→read job on a fresh 2-process fleet.  Returns
    (read_wall_seconds, {mode: data_rpc_delta}, {rid: digest})."""
    from sparkrdma_tpu.transport.simfleet import ProcessCluster

    gen = {"kind": "terasort", "records": RECORDS_PER_MAP, "seed": 0xB10C}
    with ProcessCluster(2, base_port, conf=_conf(push)) as c:
        c.register(SHUFFLE_ID, num_maps=NUM_MAPS,
                   partitioner=("hash", NUM_PARTS))
        # writes overlap across the two executor processes
        for ex in c.executors:
            for map_id in range(ex.idx, NUM_MAPS, 2):
                ex.send("write", shuffle_id=SHUFFLE_ID, map_id=map_id,
                        gen=gen)
        for ex in c.executors:
            for _ in range(ex.idx, NUM_MAPS, 2):
                ex.recv(timeout=300.0)
        mbh = c.wait_published(SHUFFLE_ID, NUM_MAPS, timeout=120.0)
        before = _fetch_rpcs(c)
        t0 = time.perf_counter()
        for rid in range(NUM_PARTS):
            c.executors[rid % 2].send(
                "read", shuffle_id=SHUFFLE_ID, start=rid, end=rid + 1,
                maps_by_host=mbh, digest=True)
        digests = {}
        for rid in range(NUM_PARTS):
            digests[rid] = c.executors[rid % 2].recv(
                timeout=300.0)["digest"]
        wall = time.perf_counter() - t0
        after = _fetch_rpcs(c)
        c.stop()
    rpcs = {m: after.get(m, 0) - before.get(m, 0) for m in after}
    return wall, rpcs, digests


def main() -> int:
    label = f"{NUM_MAPS}x{NUM_PARTS}"
    print(f"# push bench: {label}, {RECORDS_PER_MAP} records/map, "
          f"readBlockSize={READ_BLOCK}, 2-process fleet", flush=True)

    pull_wall, pull_rpcs, pull_digests = _run_mode(False, BASE_PORT)
    push_wall, push_rpcs, push_digests = _run_mode(True, BASE_PORT + 200)

    if pull_digests != push_digests:
        bad = [r for r in pull_digests if pull_digests[r] != push_digests[r]]
        print(f"FATAL: push digests diverge from pull on partitions {bad}",
              file=sys.stderr)
        return 1
    print(f"# digests agree on all {NUM_PARTS} partitions", flush=True)

    pull_data = pull_rpcs.get("pull", 0) + pull_rpcs.get("push", 0)
    push_data = push_rpcs.get("pull", 0) + push_rpcs.get("push", 0)
    ratio = pull_data / push_data if push_data else float("inf")

    emit(f"pull {label} reader data RPCs", pull_data, "rpcs", 1.0)
    emit(f"push {label} reader data RPCs", push_data, "rpcs",
         push_data / pull_data if pull_data else 0.0)
    emit(f"push {label} RPC cut", ratio, "x", ratio / 10.0)
    emit(f"push {label} merged-span fetches", push_rpcs.get("push", 0),
         "rpcs", 1.0)
    emit(f"push {label} straggler pulls", push_rpcs.get("pull", 0),
         "rpcs", 1.0)

    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    speedup = pull_wall / push_wall if push_wall else float("inf")
    tier = {
        "min_cores": 2,
        "host_note": (
            f"measured on a {cores}-core host; the wall-clock tier is a "
            "multi-core-only number (min_cores gates it in bench_gate)"),
        "results": [
            {"metric": f"pull {label} read wall", "value": round(pull_wall, 3),
             "unit": "s", "vs_baseline": 1.0},
            {"metric": f"push {label} read wall", "value": round(push_wall, 3),
             "unit": "s", "vs_baseline": round(speedup, 3)},
        ],
        "workloads": {label: {
            "num_maps": NUM_MAPS, "num_parts": NUM_PARTS,
            "records_per_map": RECORDS_PER_MAP,
            "read_block_size": READ_BLOCK,
        }},
    }
    for rec in tier["results"]:
        print(f"# [2proc] {rec['metric']}: {rec['value']} {rec['unit']}",
              flush=True)
    print(f"# pull/push read-wall ratio: {speedup:.2f}x "
          f"(host cores: {cores})", flush=True)

    write_bench_json(
        "push",
        extra={
            "smoke": SMOKE,
            "clusters": {"2": tier},
        },
        out_dir="/tmp" if SMOKE else None,
    )

    # pull only RPCs for REMOTE blocks — half the maps on a 2-executor
    # fleet — so the ideal cut is NUM_MAPS/2 (8x at the 16x16 smoke
    # size).  Hold the full 64x64 config to the ISSUE's 10x line and
    # smoke to 75% of its own ideal.
    floor = (NUM_MAPS / 2) * 0.75 if SMOKE else 10.0
    if ratio < floor:
        print(f"FATAL: RPC cut {ratio:.1f}x < the {floor:g}x "
              f"acceptance line", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
