#!/usr/bin/env python
"""GB-scale assembled-system benchmark: the 175 GB TeraSort contract's
scaling story (reference README.md:7-19) exercised end to end.

Phase A (record plane, always runs): N GB of 64-byte records stream
through the FULL assembled pipeline — writer spill files
(``shuffleSpillRecordThreshold``) → file-backed mmap commits
(``fileBackedCommitBytes``, the RdmaMappedFile path) → publish/resolve
→ windowed fetch → key-sorted merge read — with the input GENERATED in
chunks so peak RSS stays far below the dataset (the larger-than-memory
claim is measured, not asserted).

Phase B (device plane, runs when a non-CPU backend is up or
``SPARKRDMA_BENCH_DEVICE=1``): ExternalTeraSorter pushes the same
volume through device-sorted chunks + range-bucket spill files + the
bucket merge pass (models/external_sort.py).

Sizing: ``SPARKRDMA_BENCH_GB`` (default 10).  Emits one JSON line per
phase: end-to-end GB/s, with peak RSS (MB) in the metric name.
"""

import json
import os
import resource
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import ROCE_LINE_RATE_GBPS  # noqa: E402

from sparkrdma_tpu.conf import TpuShuffleConf  # noqa: E402
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager  # noqa: E402
from sparkrdma_tpu.shuffle.partitioner import RangePartitioner  # noqa: E402
from sparkrdma_tpu.transport import LoopbackNetwork  # noqa: E402
from sparkrdma_tpu.utils.columns import ColumnBatch  # noqa: E402

GB = float(os.environ.get("SPARKRDMA_BENCH_GB", "10"))
RECORD = 64  # 8B int64 key + 56B payload
N_RECORDS = int(GB * (1 << 30)) // RECORD
N_MAPS = 16
N_PARTS = 16
CHUNK = 2_000_000  # records generated/written per chunk (128 MB)
KEY_SPACE = 1 << 62


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def emit(metric: str, gbps: float) -> None:
    print(json.dumps({
        "metric": metric,
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / ROCE_LINE_RATE_GBPS, 3),
    }), flush=True)


def phase_a_record_plane(spill_dir: str) -> None:
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.serializer": "columnar",
        # spill every ~256 MB of buffered records per map task
        "spark.shuffle.tpu.shuffleSpillRecordThreshold": str(4_000_000),
        # commits of >=64 MB go to mmapped file segments
        "spark.shuffle.tpu.fileBackedCommitBytes": "64m",
        "spark.shuffle.tpu.spillDir": spill_dir,
        # bound the staging pool so its LRU actually trims between
        # partitions (the default 10g budget would retain every fetched
        # block and inflate peak RSS ~4x)
        "spark.shuffle.tpu.maxBufferAllocationSize": "1g",
    })
    net = LoopbackNetwork()
    driver = TpuShuffleManager(
        conf, is_driver=True, network=net, stage_to_device=False,
    )
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net, port=47800 + i * 10,
            executor_id=str(i), stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)

    # uniform keys: exact equal-frequency splitters known a priori
    splitters = [
        (p + 1) * (KEY_SPACE // N_PARTS) for p in range(N_PARTS - 1)
    ]
    # a sorted sample of exactly P-1 values becomes the splitter list
    part = RangePartitioner(N_PARTS, splitters)
    assert part.splitters == splitters

    handle = driver.register_shuffle(90, N_MAPS, part, key_ordering=True)
    per_map = N_RECORDS // N_MAPS
    # one shared random payload pool, sliced per chunk: generating
    # fresh PCG64 bytes for every record would dominate the write
    # timing (the shuffle doesn't care that payload bytes repeat)
    pool = np.frombuffer(
        np.random.default_rng(99).bytes(CHUNK * 56), dtype="V56"
    )
    t0 = time.perf_counter()
    maps_by_host = {}
    for m in range(N_MAPS):
        ex = executors[m % len(executors)]
        w = ex.get_writer(handle, m)
        rng = np.random.default_rng(1000 + m)
        left = per_map
        while left > 0:  # streamed generation: input never resident
            n = min(CHUNK, left)
            keys = rng.integers(0, KEY_SPACE, n, dtype=np.int64)
            w.write_columns(ColumnBatch(keys, pool[:n]))
            left -= n
        w.stop(True)
        maps_by_host.setdefault(ex.local_smid, []).append(m)
    t_write = time.perf_counter() - t0
    print(f"# phase A write+spill+commit: {t_write:.1f}s "
          f"(rss {rss_mb():.0f} MB)", flush=True)

    # read: fetch every partition's blocks, deserialize to columns,
    # merge the key-sorted runs (np.sort over presorted runs), verify
    total_read = 0
    total_records = 0
    t1 = time.perf_counter()
    for p in range(N_PARTS):
        ex = executors[p % len(executors)]
        reader = ex.get_reader(handle, p, p + 1, maps_by_host)
        deser = ex.serializer.deserialize_columns
        key_parts = []
        for data in reader._iter_block_bytes():
            total_read += len(data)
            for b in deser(data):
                total_records += len(b)
                if not b.key_sorted:
                    raise AssertionError("expected key-sorted blocks")
                # copy: a keys VIEW would pin the whole block buffer
                # (keys + payload) in memory until the merge
                key_parts.append(b.keys.copy())
        if key_parts:
            merged = np.sort(np.concatenate(key_parts), kind="stable")
            lo = splitters[p - 1] if p else 0
            hi = splitters[p] if p < N_PARTS - 1 else KEY_SPACE
            if len(merged) and not (
                lo <= int(merged[0]) and int(merged[-1]) < hi
            ):
                raise AssertionError(f"partition {p} range violated")
    t_read = time.perf_counter() - t1
    assert total_records == per_map * N_MAPS, (
        f"lost records: {total_records} != {per_map * N_MAPS}"
    )
    print(f"# phase A fetch+merge: {t_read:.1f}s, "
          f"{total_read / 1e9:.2f} GB fetched (rss {rss_mb():.0f} MB)",
          flush=True)
    payload = per_map * N_MAPS * RECORD
    gbps = payload / (t_write + t_read) / 1e9
    emit(
        f"assembled {GB:g}GB record-plane sortByKey "
        f"(spill + file-backed commit + fetch + merge, "
        f"peak rss {rss_mb():.0f} MB)",
        gbps,
    )
    driver.unregister_shuffle(90)
    for m in executors:
        m.unregister_shuffle(90)
    for m in executors + [driver]:
        m.stop()


def phase_b_device_plane(spill_dir: str) -> None:
    # explicit opt-in ONLY: merely asking jax for its backend
    # INITIALIZES it, which hangs indefinitely when the tunneled TPU
    # grant is wedged (tools/TPU_TODO.md) — auto-detection is a hang
    if os.environ.get("SPARKRDMA_BENCH_DEVICE") != "1":
        print("# phase B skipped (set SPARKRDMA_BENCH_DEVICE=1 after "
              "probing the backend; init hangs when the grant is "
              "wedged)", flush=True)
        return
    import jax

    from sparkrdma_tpu.models.external_sort import ExternalTeraSorter

    backend = jax.default_backend()
    n = N_RECORDS  # 8B records on the device plane (int32 kv pairs)
    chunk = 8_000_000
    sorter = ExternalTeraSorter(
        num_buckets=max(64, n // chunk), spill_dir=spill_dir
    )

    def chunks():
        rng = np.random.default_rng(7)
        left = n
        while left > 0:
            c = min(chunk, left)
            yield (
                rng.integers(0, 1 << 31, c, dtype=np.int32),
                rng.integers(0, 1 << 31, c, dtype=np.int32),
            )
            left -= c

    t0 = time.perf_counter()
    out_records = 0
    last_max = None
    for sk, _sv in sorter.sort_chunks(chunks()):
        out_records += len(sk)
        if len(sk):
            if last_max is not None and int(sk[0]) < last_max:
                raise AssertionError("bucket order violated")
            last_max = int(sk[-1])
    dt = time.perf_counter() - t0
    assert out_records == n, f"lost records: {out_records} != {n}"
    gbps = n * 8 / dt / 1e9
    emit(
        f"external device TeraSort {n * 8 / 1e9:.1f}GB "
        f"({backend} backend, chunked spill + bucket merge, "
        f"peak rss {rss_mb():.0f} MB)",
        gbps,
    )


def main():
    with tempfile.TemporaryDirectory(prefix="sparkrdma_10gb_") as d:
        phase_a_record_plane(d)
        phase_b_device_plane(d)


if __name__ == "__main__":
    main()
