#!/usr/bin/env python
"""BASELINE config 4: HiBench Sort + WordCount (hash-partitioned shuffle).

Two device-plane jobs (BASELINE.md config 4):

- **Sort**: hash-partitioned shuffle followed by per-partition sort —
  measured through the TeraSorter (range partition subsumes it; the
  exchange volume is identical).
- **WordCount**: reduceByKey(+) — hash partition → all_to_all →
  segment reduction, ONE XLA program per step.

Reported as shuffled bytes per second per chip.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import (
    ROCE_LINE_RATE_GBPS,
    emit,
    maybe_spoof_cpu,
    time_iters,
    zipf_keys,
)

from sparkrdma_tpu.models.wordcount import WordCounter
from sparkrdma_tpu.parallel.mesh import make_mesh


def main():
    maybe_spoof_cpu()
    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 23
    n = 1 << log2
    mesh = make_mesh()
    wc = WordCounter(mesh)
    rng = np.random.default_rng(7)
    # Zipf word ids (rank-preserving): heavy keys exercise the
    # skew/capacity machinery with an intact distribution head
    keys = jax.device_put(
        zipf_keys(rng, 1.3, n, 100_000, dtype=np.int32), wc.sharding
    )
    vals = jax.device_put(jnp.ones(n, jnp.int32), wc.sharding)
    n_local = n // wc.n_devices
    cap = wc._capacity(n_local, factor=4.0)
    # valid=None: on one chip this engages the validity-free sort fast
    # path; on a mesh the step builds the all-ones column itself
    valid = (
        None if wc.n_devices == 1
        else jax.device_put(jnp.ones(n, jnp.int32), wc.sharding)
    )

    def run():
        (uniq, sums, counts, n_unique, fill), _ = wc.count_device(
            keys, vals, valid, capacity=cap
        )
        return uniq, n_unique

    dt = time_iters(run, iters=10)
    n_chips = wc.n_devices
    gbps_chip = n * 8 / dt / 1e9 / n_chips
    emit(
        f"wordcount reduceByKey throughput per chip ({n} records, "
        f"{n_chips} chip(s))",
        gbps_chip, "GB/s/chip", gbps_chip / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
