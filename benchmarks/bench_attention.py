#!/usr/bin/env python
"""Long-context sequence-parallel attention throughput.

Measures the ring-attention schedule (Pallas blockwise kernel + ppermute
K/V circulation) on whatever devices are visible, reported as attention
TFLOP/s per chip.  The reference has no model plane — this benchmarks
the long-context capability SURVEY.md §5 marks first-class for the
rebuild; ``vs_baseline`` is vs a 10 TFLOP/s round figure for a
flash-attention CPU/GPU-class single-node baseline of the reference's
2015 hardware era (the README cluster's Xeon E5-2697v3 peaks ~1.2
fp32 TFLOP/s/node).

    python benchmarks/bench_attention.py [seq_len] [n_heads] [d_head] [dtype]
"""

import sys

import jax
import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import emit, maybe_spoof_cpu, time_iters

from sparkrdma_tpu.models.ring_attention import ring_attention
from sparkrdma_tpu.parallel.mesh import make_mesh

BASELINE_TFLOPS = 10.0


def main():
    maybe_spoof_cpu()
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    H = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    d = int(sys.argv[3]) if len(sys.argv) > 3 else 128
    dtype = sys.argv[4] if len(sys.argv) > 4 else "bfloat16"
    mesh = make_mesh()
    D = len(list(mesh.devices.flat))
    rng = np.random.default_rng(0)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS

    # place inputs once: steady state keeps activations device-resident,
    # and the tunneled host link would otherwise dominate the timing
    import jax.numpy as jnp

    sharding = NamedSharding(mesh, P(None, EXCHANGE_AXIS, None))
    q, k, v = (
        jax.device_put(
            jnp.asarray(
                rng.standard_normal((H, S, d)).astype(np.float32),
                dtype=jnp.dtype(dtype),
            ),
            sharding,
        )
        for _ in range(3)
    )

    def run():
        return ring_attention(q, k, v, mesh=mesh, causal=True)

    dt = time_iters(run, iters=10)
    # causal attention: 2 matmuls of S*S/2 * d MACs per head
    flops = 2 * 2 * H * (S * S / 2) * d
    tflops_chip = flops / dt / 1e12 / D
    emit(
        f"ring attention throughput per chip (S={S}, H={H}, d={d}, "
        f"{dtype}, {D} chip(s))",
        tflops_chip, "TFLOP/s/chip", tflops_chip / BASELINE_TFLOPS,
    )


if __name__ == "__main__":
    main()
