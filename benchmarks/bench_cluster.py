#!/usr/bin/env python
"""Cluster bench tier (ISSUE 17): terasort/wordcount end-to-end across
REAL executor processes (transport/simfleet.ProcessCluster), plus the
native hot-path kernel microbench.

Per process count (2..8, ``clusters`` section of the output, keyed by
count so tools/bench_gate.py gates each tier independently):

- terasort and wordcount wall clock + rows/s through the full
  write → publish → fetch → read cycle over real TCP sockets,
- bit-exactness: every partition digest must equal the single-process
  loopback reference run of the SAME generated workload,
- per-process census (CPU seconds, fds, threads) summed fleet-wide,
- fetch/decode wait split from the children's metrics registries and
  the derived read-overlap ratio (1 - wait/wall, clamped at 0),
- control-plane RPC counts (transport msgs sent/received).

Flat results carry the native-kernel microbench: frame-walk, CRC
batch, and block gather, each native vs its pure-Python fallback loop
on small-frame workloads where per-call interpreter overhead dominates
— the ISSUE 17 acceptance line is >=2x on this 1-core host.

On a 1-core host the multi-process tiers can only timeslice, so the
rows/s lines are STRUCTURAL (bit-exact results, census, RPC counts),
not a parallel speedup claim — the host note records this (the PR 14
precedent).

    BENCH_SMOKE=1 python benchmarks/bench_cluster.py
"""

import os
import sys
import time
import zlib

sys.path.insert(0, ".")
from benchmarks.common import emit, write_bench_json

SMOKE = bool(os.environ.get("BENCH_SMOKE"))

PROC_COUNTS = [2] if SMOKE else [2, 4]
NUM_PARTS = 4 if SMOKE else 8
RECORDS_PER_MAP = 1500 if SMOKE else 20_000
BASE_PORT = 25200

WORKLOADS = {
    "terasort": {"kind": "terasort", "records": RECORDS_PER_MAP,
                 "value_len": 64},
    "wordcount": {"kind": "wordcount", "records": RECORDS_PER_MAP,
                  "vocab": 997},
}


def _conf_map(extra=None):
    m = {
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
        "spark.shuffle.tpu.connectTimeout": "15s",
        "spark.shuffle.tpu.metrics": True,
    }
    m.update(extra or {})
    return m


def single_process_reference(gen, num_maps, base_port):
    """The same generated workload through ONE process over loopback:
    the bit-exactness reference and the no-parallelism baseline."""
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import LoopbackNetwork
    from sparkrdma_tpu.transport.simfleet import _gen_records, records_digest

    net = LoopbackNetwork()
    conf = TpuShuffleConf(_conf_map({
        "spark.shuffle.tpu.driverPort": base_port,
    }))
    driver = TpuShuffleManager(conf, is_driver=True, network=net,
                               stage_to_device=False)
    ex = TpuShuffleManager(conf, is_driver=False, network=net,
                           port=base_port + 50, executor_id="0",
                           stage_to_device=False)
    handle = ex.register_shuffle(1, num_maps, HashPartitioner(NUM_PARTS))
    t0 = time.perf_counter()
    for map_id in range(num_maps):
        w = ex.get_writer(handle, map_id)
        w.write(_gen_records(gen, map_id))
        w.stop(True)
    mbh = {ex.local_smid: list(range(num_maps))}
    digests, total = [], 0
    for p in range(NUM_PARTS):
        records = list(ex.get_reader(handle, p, p + 1, mbh).read())
        total += len(records)
        digests.append(records_digest(records))
    wall = time.perf_counter() - t0
    ex.stop()
    driver.stop()
    return digests, total, wall


def _counter_sum(snapshot, name):
    return sum(c["value"] for c in snapshot.get("counters", [])
               if c["name"] == name)


def cluster_run(n_procs, gen, base_port):
    """One workload through an n-process fleet; returns timing +
    digests + fleet census/metrics."""
    from sparkrdma_tpu.transport.simfleet import ProcessCluster

    num_maps = n_procs
    with ProcessCluster(n_procs, base_port, conf=_conf_map()) as c:
        c.register(1, num_maps=num_maps, partitioner=("hash", NUM_PARTS))
        t0 = time.perf_counter()
        # fan the map tasks out, THEN collect — per-pipe FIFO keeps
        # reply order deterministic while the fleet works in parallel
        for map_id in range(num_maps):
            c.executors[map_id % n_procs].send(
                "write", shuffle_id=1, map_id=map_id, gen=gen)
        for map_id in range(num_maps):
            c.executors[map_id % n_procs].recv(300.0)
        c.wait_published(1, num_maps)
        write_wall = time.perf_counter() - t0

        t1 = time.perf_counter()
        mbh = c.driver.maps_by_host(1)
        for p in range(NUM_PARTS):
            c.executors[p % n_procs].send(
                "read", shuffle_id=1, start=p, end=p + 1,
                maps_by_host=mbh, digest=True)
        digests, total = [], 0
        for p in range(NUM_PARTS):
            out = c.executors[p % n_procs].recv(300.0)
            digests.append(out["digest"])
            total += out["records"]
        read_wall = time.perf_counter() - t1

        census = c.census()
        fleet = {"cpu_user_s": 0.0, "cpu_sys_s": 0.0, "fds": 0,
                 "threads": 0, "fetch_wait_ms": 0, "decode_wait_ms": 0,
                 "msgs_sent": 0, "msgs_received": 0}
        for info in census["executors"].values():
            cen, snap = info["census"], info["metrics"]
            fleet["cpu_user_s"] += cen["cpu_user_s"]
            fleet["cpu_sys_s"] += cen["cpu_sys_s"]
            fleet["fds"] += cen["fds"]
            fleet["threads"] += cen["threads"]
            fleet["fetch_wait_ms"] += _counter_sum(
                snap, "shuffle_fetch_wait_ms_total")
            fleet["decode_wait_ms"] += _counter_sum(
                snap, "shuffle_decode_wait_ms_total")
            fleet["msgs_sent"] += _counter_sum(
                snap, "transport_msgs_sent_total")
            fleet["msgs_received"] += _counter_sum(
                snap, "transport_msgs_received_total")
        c.stop()
        collected = c.collect()
        return {
            "write_wall_s": write_wall,
            "read_wall_s": read_wall,
            "digests": digests,
            "records": total,
            "num_maps": num_maps,
            "fleet": fleet,
            "census_procs": 1 + len(census["executors"]),
            "obs_dumps": len(collected["dump_paths"]),
        }


# -- native hot-path kernel microbench --------------------------------------

def _time_best(fn, reps=9):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def kernel_microbench():
    """Native frame-walk / CRC-batch / gather vs their pure-Python
    fallback loops, on many-small-frame workloads where per-call
    interpreter overhead dominates (the per-process hot path)."""
    import numpy as np

    from sparkrdma_tpu.memory import staging
    from sparkrdma_tpu.utils.serde import PickleSerializer

    n_frames = 4000 if SMOKE else 8000
    body = b"x" * 72
    buf = bytearray()
    spans = []
    for _ in range(n_frames):
        start = len(buf)  # spans cover the 4B length prefix + body
        buf += len(body).to_bytes(4, "little") + body
        spans.append((start, len(buf)))
    buf = bytes(buf)
    ser = PickleSerializer()
    view = memoryview(buf)

    out = {}

    # frame walk: serde's native-first path vs its Python loop (the
    # fallback is forced by patching the staging hook, so both sides
    # run the REAL production code)
    native_walk = _time_best(lambda: ser.frame_spans(view))
    hook = staging.native_frame_spans
    staging.native_frame_spans = lambda *a, **k: None
    try:
        py_spans = ser.frame_spans(view)
        py_walk = _time_best(lambda: ser.frame_spans(view))
    finally:
        staging.native_frame_spans = hook
    assert ser.frame_spans(view) == py_spans == spans
    out["frame_walk"] = (py_walk, native_walk)

    # CRC batch: one native crc32_spans call vs the per-span zlib loop
    # (span table as an int64 array, the records_digest idiom — a
    # tuple-list would spend the win on list→ndarray conversion)
    spans_arr = np.asarray(spans, np.int64)

    def _py_crc():
        return [zlib.crc32(view[a:b]) for a, b in spans]

    native_crc = staging.native_crc32_spans(buf, spans_arr)
    if native_crc is not None:
        assert list(native_crc) == _py_crc()
        t_native_crc = _time_best(
            lambda: staging.native_crc32_spans(buf, spans_arr))
        out["crc_batch"] = (_time_best(_py_crc), t_native_crc)

    # gather: one native batched-memcpy call vs the numpy
    # slice-assignment loop bulk._assemble falls back to
    n_blocks = len(spans)
    srcs = [np.frombuffer(buf, np.uint8, b - a, a) for a, b in spans]
    lens = [len(s) for s in srcs]
    offs = [0] * n_blocks
    acc = 0
    for i, n in enumerate(lens):
        offs[i] = acc
        acc += n
    dst = np.empty(acc, np.uint8)
    addrs = [int(s.ctypes.data) for s in srcs]

    def _py_gather():
        for s, off, n in zip(srcs, offs, lens):
            dst[off:off + n] = s

    _py_gather()
    expect = dst.copy()
    if staging.native_gather_blocks(dst, addrs, lens, offs):
        dst[:] = 0
        assert staging.native_gather_blocks(dst, addrs, lens, offs)
        assert np.array_equal(dst, expect)
        out["gather"] = (
            _time_best(_py_gather),
            _time_best(
                lambda: staging.native_gather_blocks(dst, addrs, lens, offs)
            ),
        )
    return n_frames, out


def main():
    port = BASE_PORT
    clusters = {}
    bit_exact = True
    reference = {}
    for name, gen in WORKLOADS.items():
        for n_procs in PROC_COUNTS:
            ref_key = (name, n_procs)
            # reference maps == cluster maps so the workloads match
            reference[ref_key] = single_process_reference(
                gen, n_procs, port)
            port += 100
    for n_procs in PROC_COUNTS:
        # multi-process numbers are only meaningful with real cores
        # under them: tools/bench_gate.py skips the tier (with a note)
        # on hosts below min_cores instead of gating timeslice noise
        tier = {
            "results": [], "workloads": {},
            "min_cores": 2 if n_procs >= 2 else 0,
        }
        for name, gen in WORKLOADS.items():
            run = cluster_run(n_procs, gen, port)
            port += 1000
            ref_digests, ref_total, ref_wall = reference[(name, n_procs)]
            exact = (run["digests"] == ref_digests
                     and run["records"] == ref_total)
            bit_exact = bit_exact and exact
            rows = run["num_maps"] * gen["records"]
            wall = run["write_wall_s"] + run["read_wall_s"]
            fleet = run["fleet"]
            wait_ms = fleet["fetch_wait_ms"] + fleet["decode_wait_ms"]
            overlap = max(0.0, 1.0 - wait_ms / 1000.0 / run["read_wall_s"]) \
                if run["read_wall_s"] > 0 else 0.0
            tier["workloads"][name] = {
                "bit_exact": exact,
                "records": run["records"],
                "single_process_wall_s": round(ref_wall, 4),
                "fleet": fleet,
                "census_procs": run["census_procs"],
                "obs_dumps": run["obs_dumps"],
            }
            for rec in (
                (f"{name} end-to-end", rows / wall, "rows/s", 1.0),
                (f"{name} bit-exact vs single-process",
                 1.0 if exact else 0.0, "bool", 1.0),
                (f"{name} fleet cpu (user+sys)",
                 fleet["cpu_user_s"] + fleet["cpu_sys_s"], "cpu-s", 1.0),
                (f"{name} fetch wait", fleet["fetch_wait_ms"],
                 "ms.cum", 1.0),
                (f"{name} decode wait", fleet["decode_wait_ms"],
                 "ms.cum", 1.0),
                (f"{name} read overlap ratio", overlap, "ratio", 1.0),
                (f"{name} transport msgs", fleet["msgs_sent"],
                 "msgs.cum", 1.0),
            ):
                metric, value, unit, vs = rec
                emit(f"[{n_procs}proc] {metric}", value, unit, vs)
                tier["results"].append({
                    "metric": metric, "value": round(float(value), 3),
                    "unit": unit, "vs_baseline": vs,
                })
        clusters[str(n_procs)] = tier

    n_frames, kernels = kernel_microbench()
    kernel_speedups = {}
    for kname, (py_s, native_s) in kernels.items():
        speedup = py_s / native_s if native_s > 0 else 0.0
        kernel_speedups[kname] = round(speedup, 2)
        emit(f"native {kname} ({n_frames} frames) vs python loop",
             speedup, "x", speedup / 2.0)  # the >=2x acceptance line
        emit(f"native {kname} per-frame", native_s / n_frames * 1e6,
             "us", 1.0)

    ncpu = os.cpu_count() or 1
    host_note = None
    if ncpu == 1:
        host_note = (
            "1-core bench container: executor processes timeslice one "
            "core, so the multi-process tiers cannot show a parallel "
            "speedup here by construction — the rows/s lines are "
            "structural acceptance (bit-exact digests vs the "
            "single-process loopback reference, full process census, "
            "RPC counts, obs dumps from every process), the PR 14 "
            "precedent.  The native-kernel speedups ARE 1-core-"
            "measurable (pure interpreter-overhead elimination) and "
            "carry the >=2x acceptance."
        )
    assert bit_exact, "cluster digests diverged from single-process run"
    write_bench_json(
        "cluster",
        extra={
            "proc_counts": PROC_COUNTS,
            "num_partitions": NUM_PARTS,
            "records_per_map": RECORDS_PER_MAP,
            "host_cores": ncpu,
            "host_note": host_note,
            "bit_exact": bit_exact,
            "kernel_speedups": kernel_speedups,
            "clusters": clusters,
        },
        out_dir="/tmp" if SMOKE else None,
    )


if __name__ == "__main__":
    import jax

    # record-plane bench: never touches a chip; a wedged tunnel grant
    # must not hang backend init (bench_skew idiom)
    jax.config.update("jax_platforms", "cpu")
    main()
