#!/usr/bin/env python
"""BASELINE config 1: local[*] groupByKey through the record plane.

The reference measures Spark local[*] with the stock SortShuffleManager
as its CPU-only control (BASELINE.md config 1).  Here the same job —
groupByKey over (key, 64B payload) records — runs through our full
record plane: write → publish → resolve → fetch → read over the
loopback transport, with every executor in one process.

The record plane is COLUMNAR (conf ``serializer=columnar``): records
travel as fixed-width key/value columns, partitioning and grouping are
numpy kernels plus the native prefetching row gather, and blocks are
committed key-sorted so readers merge views — the unsafe-row analog of
the reference wrapping Spark's UnsafeShuffleWriter
(RdmaWrapperShuffleWriter.scala:85-101).  The metric is end-to-end
shuffled payload bytes per second on the record (host) plane;
``vs_baseline`` is vs the RoCE line rate the reference's NIC plane is
bounded by (the record plane is NOT expected to reach it — that is the
device plane's job, configs 3-5).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import ROCE_LINE_RATE_GBPS, emit

from sparkrdma_tpu.api import TpuShuffleContext
from sparkrdma_tpu.conf import TpuShuffleConf

N_RECORDS = 1_000_000
PAYLOAD = 64  # bytes per record
N_KEYS = 512
REPS = 5


def _run_config(keys, label):
    rng = np.random.default_rng(1)
    vals = np.frombuffer(rng.bytes(N_RECORDS * PAYLOAD), dtype=f"S{PAYLOAD}")
    conf = TpuShuffleConf({"spark.shuffle.tpu.serializer": "columnar"})

    # local[*] semantics: one executor per core (on a single-core box
    # extra threads only pay GIL contention — measured 40% slower)
    cores = os.cpu_count() or 1
    n_exec = max(1, min(4, cores))
    with TpuShuffleContext(num_executors=n_exec, conf=conf,
                           stage_to_device=False,
                           tasks_per_executor=2 if cores > 1 else 1) as ctx:
        ds = ctx.parallelize_columns(keys, vals, num_slices=2 * n_exec)
        out = ds.group_by_key(num_partitions=8).collect()  # warm + check
        n_groups = len(set(keys.tolist()))
        assert len(out) == n_groups, f"{n_groups} groups != {len(out)}"
        assert sum(len(vs) for _, vs in out) == N_RECORDS
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            ds.group_by_key(num_partitions=8).collect()
            best = min(best, time.perf_counter() - t0)

    gbps = N_RECORDS * PAYLOAD / best / 1e9
    emit(
        f"local[*] groupByKey columnar record-plane throughput "
        f"({N_RECORDS} x {PAYLOAD}B records, {label})",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )


def main():
    rng = np.random.default_rng(0)
    # narrow-key shape: the fused native hash_partition_order fast path
    # (krange * P <= 65536) — the round-3 headline shape
    _run_config(
        rng.integers(0, N_KEYS, N_RECORDS).astype(np.int64),
        "narrow keys",
    )
    # wide-RANGE keys (VERDICT r3 item 8): same 512 distinct keys, but
    # spread over a 2^60 keyspace so the fused fast path is ineligible
    # and the write side routes through the stable LSD radix argsort —
    # the honest second row (identical group cardinality, only the
    # partition/sort machinery differs)
    choices = rng.integers(0, 1 << 60, N_KEYS, dtype=np.int64)
    _run_config(
        choices[rng.integers(0, N_KEYS, N_RECORDS)],
        "wide-range keys",
    )


if __name__ == "__main__":
    main()
