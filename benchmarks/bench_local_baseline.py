#!/usr/bin/env python
"""BASELINE config 1: local[*] groupByKey through the record plane.

The reference measures Spark local[*] with the stock SortShuffleManager
as its CPU-only control (BASELINE.md config 1).  Here the same job —
groupByKey over (key, payload) records — runs through our full record
plane: write → publish → resolve → fetch → read over the loopback
transport, with every executor in one process.  The metric is
end-to-end shuffled payload bytes per second on the record (host) plane;
``vs_baseline`` is vs the RoCE line rate the reference's NIC plane is
bounded by (the record plane is NOT expected to reach it — that is the
device plane's job, configs 3-5).
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import ROCE_LINE_RATE_GBPS, emit

from sparkrdma_tpu.api import TpuShuffleContext

N_RECORDS = 200_000
PAYLOAD = 64  # bytes per record
N_KEYS = 512


def main():
    rng = np.random.default_rng(0)
    keys = rng.integers(0, N_KEYS, N_RECORDS)
    payload = bytes(PAYLOAD)
    records = [(int(k), payload) for k in keys]

    with TpuShuffleContext(num_executors=4, stage_to_device=False) as ctx:
        ds = ctx.parallelize(records, num_slices=8)
        t0 = time.perf_counter()
        out = ds.group_by_key(num_partitions=8).collect()
        dt = time.perf_counter() - t0

    assert len(out) == N_KEYS, f"expected {N_KEYS} groups, got {len(out)}"
    assert sum(len(vs) for _, vs in out) == N_RECORDS
    gbps = N_RECORDS * PAYLOAD / dt / 1e9
    emit(
        f"local[*] groupByKey record-plane throughput ({N_RECORDS} x "
        f"{PAYLOAD}B records)",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
