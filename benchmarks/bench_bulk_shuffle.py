#!/usr/bin/env python
"""Bulk-synchronous collective shuffle end to end (readPlane=bulk) plus
the windowed-plane byte-throughput bench for the zero-copy pipelined
data path.

Part 1 — the original record job (shared workload from
benchmarks/common.py) on the bulk-synchronous plane: map phase, then
ONE plan barrier + ONE symmetric ``exchange_bytes`` moves every stream
(shuffle/bulk.py).

Part 2 — the windowed plane at a ≥64 MiB working set: maps publish,
then driver-planned window collectives move the bytes with the
double-buffered pipeline ON and OFF.  Reports GB/s for both, the
pipeline speedup, and the plan_wait vs exchange span split from the
tracer (the round-5 "unmeasured plan-fetch overlap" item), all
embedded in BENCH_bulk_shuffle.json next to the metrics snapshot
(copy-bytes-avoided, assembly overlap ratio).

Needs ≥4 mesh devices; on the single-chip bench host it re-execs onto
a spoofed 8-device CPU mesh, so the numbers gauge the plane's
overhead, not TPU silicon.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WINDOWED_TOTAL_MIB = 96      # working set (acceptance floor: 64 MiB)
WINDOWED_EXECUTORS = 4
WINDOWED_MAPS = 8
WINDOWED_REPS = 3

# pre-zero-copy reference (commit 23de5aa, this same bench run against
# the legacy b"".join / tobytes data path on the same spoofed-CPU
# host) — the "before" half of the before/after record in the JSON
PRE_PR_REFERENCE = {
    "commit": "23de5aa",
    "windowed_gbps": 0.085,
    "pipelined_s": 1.1787,
    "serial_s": 1.2078,
    "plan_wait_ms": {"pipelined": 54.6, "serial": 45.2},
    "exchange_ms": {"pipelined": 16433.8, "serial": 14352.3},
}


def _windowed_bench(pipelined: bool, base_port: int):
    """Time the windowed exchange of a WINDOWED_TOTAL_MIB working set
    across WINDOWED_EXECUTORS in-process executors; returns
    (best_seconds, payload_bytes, plan_wait_ms, exchange_ms)."""
    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.parallel.exchange import TileExchange
    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.bulk import (
        BulkExchangeReader,
        BulkShuffleSession,
        iter_plan_blocks,
    )
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import LoopbackNetwork
    from sparkrdma_tpu.utils.trace import get_tracer

    E = WINDOWED_EXECUTORS
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.serializer": "columnar",
        "spark.shuffle.tpu.readPlane": "windowed",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
        "spark.shuffle.tpu.bulkPipelineWindows": str(pipelined),
        "spark.shuffle.tpu.exchangeTileBytes": "4m",
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
        "spark.shuffle.tpu.metrics": "true",
        "spark.shuffle.tpu.trace": "true",
        # managers dump the trace at stop(); keep the litter out of
        # the repo root (the spans are read via get_tracer().events)
        "spark.shuffle.tpu.tracePath": os.path.join(
            __import__("tempfile").gettempdir(),
            "bench_bulk_shuffle_trace.json",
        ),
    })
    net = LoopbackNetwork()
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 100 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(E)
    ]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all(len(e._peers) == E for e in executors):
            break
        time.sleep(0.01)

    payload = 1024
    total_bytes = WINDOWED_TOTAL_MIB << 20
    n_records = total_bytes // (payload + 8)
    per_map = n_records // WINDOWED_MAPS
    rng = np.random.default_rng(0)
    num_parts = 2 * E
    part = HashPartitioner(num_parts)

    best = float("inf")
    moved = 0
    try:
        for rep in range(WINDOWED_REPS):
            sid = 900 + rep
            handle = driver.register_shuffle(sid, WINDOWED_MAPS, part)
            for m in range(WINDOWED_MAPS):
                keys = rng.integers(
                    0, 1 << 30, per_map
                ).astype(np.int64)
                vals = np.frombuffer(
                    rng.bytes(per_map * payload), dtype=f"S{payload}"
                )
                w = executors[m % E].get_writer(handle, m)
                w.write(list(zip(keys.tolist(), vals.tolist())))
                w.stop(True)

            session = BulkShuffleSession(
                TileExchange.from_conf(conf, make_mesh(E)), E,
                timeout_s=conf.bulk_barrier_timeout_ms / 1000.0,
            )
            consumed = [0] * E
            errors = {}

            def read_task(i, sid=sid, consumed=consumed,
                          errors=errors, session=session):
                try:
                    r = BulkExchangeReader(
                        executors[i], session=session
                    )
                    n = 0
                    for plan, nE, row in r._iter_windowed_exchanges(
                        sid
                    ):
                        for _s, _m, _r, blk in iter_plan_blocks(
                            plan, nE, row
                        ):
                            n += len(blk)
                    consumed[i] = n
                except BaseException as err:  # pragma: no cover
                    errors[i] = err

            threads = [
                threading.Thread(target=read_task, args=(i,),
                                 daemon=True)
                for i in range(E)
            ]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            took = time.monotonic() - t0
            assert not errors, errors
            moved = sum(consumed)
            assert moved > 0, "no bytes moved"
            best = min(best, took)
            driver.unregister_shuffle(sid)
            for e in executors:
                e.unregister_shuffle(sid)
    finally:
        spans = get_tracer().events
        for m in executors + [driver]:
            m.stop()
    plan_wait_ms = sum(
        ev.get("dur", 0) for ev in spans
        if ev.get("name") == "shuffle.windowed.plan_wait"
    ) / 1000.0
    exchange_ms = sum(
        ev.get("dur", 0) for ev in spans
        if ev.get("name") == "shuffle.bulk.exchange"
    ) / 1000.0
    get_tracer().clear()
    return best, moved, plan_wait_ms, exchange_ms


def main():
    from benchmarks.common import (
        ROCE_LINE_RATE_GBPS,
        canonical_record_workload,
        emit,
        enable_metrics,
        ensure_multidevice,
        metrics_snapshot,
        time_group_by_key,
        write_bench_json,
    )

    ensure_multidevice(__file__)

    from sparkrdma_tpu.api import TpuShuffleContext
    from sparkrdma_tpu.conf import TpuShuffleConf

    n_records, payload, n_keys = 1_000_000, 64, 512
    keys, vals = canonical_record_workload(n_records, payload, n_keys)
    conf = TpuShuffleConf()
    conf.set("serializer", "columnar")
    conf.set("readPlane", "bulk")
    conf.set("exchangeTileBytes", "16m")
    enable_metrics(conf)

    # stage_to_device pinned False on BOTH compared planes (it is now
    # the windowed/bulk default too): their exchanges read blocks
    # host-side, so HBM staging would only add a per-block device
    # round-trip; the BASELINE cross-plane ratio compares plane design
    # with identical staging either way
    with TpuShuffleContext(
        num_executors=4, conf=conf, stage_to_device=False
    ) as ctx:
        best = time_group_by_key(ctx, keys, vals, n_keys)

    gbps = n_records * payload / best / 1e9
    emit(
        f"bulk-plane groupByKey end-to-end throughput "
        f"({n_records} x {payload}B records, plan barrier + one "
        f"symmetric collective)",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )

    # -- windowed plane, zero-copy pipelined data path ----------------------
    from sparkrdma_tpu.metrics import get_registry

    get_registry().enabled = True

    def counter_totals() -> dict:
        totals: dict = {}
        for c in metrics_snapshot().get("counters", []):
            totals[c["name"]] = totals.get(c["name"], 0) + c["value"]
        return totals

    # snapshot-deltas isolate the PIPELINED run's counters: the
    # process-cumulative registry also carries Part 1's bulk plane and
    # the serial run, which would dilute the overlap ratio
    base_counters = counter_totals()
    t_pipe, moved, pw_pipe, ex_pipe = _windowed_bench(
        True, base_port=53100
    )
    pipe_counters = counter_totals()
    pipe_delta = {
        k: v - base_counters.get(k, 0)
        for k, v in pipe_counters.items()
    }
    pipe_gbps = moved / t_pipe / 1e9
    emit(
        f"windowed-plane exchange throughput, pipelined "
        f"({moved >> 20} MiB working set, double-buffered windows)",
        pipe_gbps, "GB/s", pipe_gbps / ROCE_LINE_RATE_GBPS,
    )
    t_ser, moved_s, pw_ser, ex_ser = _windowed_bench(
        False, base_port=53500
    )
    ser_gbps = moved_s / t_ser / 1e9
    emit(
        "windowed-plane exchange throughput, serial (pipeline off)",
        ser_gbps, "GB/s", ser_gbps / ROCE_LINE_RATE_GBPS,
    )
    emit(
        "windowed pipeline speedup (pipelined vs serial wall-clock; "
        "<1 expected on a single-core host, where nothing can overlap)",
        t_ser / t_pipe, "x", t_ser / t_pipe,
    )
    best_gbps = max(pipe_gbps, ser_gbps)
    emit(
        "windowed-plane zero-copy speedup vs pre-PR data path "
        "(best mode on this host vs commit "
        f"{PRE_PR_REFERENCE['commit']})",
        best_gbps / PRE_PR_REFERENCE["windowed_gbps"], "x",
        best_gbps / PRE_PR_REFERENCE["windowed_gbps"],
    )

    asm_us = pipe_delta.get("exchange_assembly_us_total", 0)
    asm_overlap_us = pipe_delta.get(
        "exchange_assembly_overlapped_us_total", 0
    )
    overlap_ratio = (asm_overlap_us / asm_us) if asm_us else 0.0
    emit(
        "windowed assembly overlap ratio (assembly ms hidden behind "
        "collectives / total assembly ms)",
        overlap_ratio, "ratio", overlap_ratio,
    )
    write_bench_json("bulk_shuffle", extra={
        "windowed": {
            "working_set_bytes": moved,
            "pipelined_s": round(t_pipe, 4),
            "serial_s": round(t_ser, 4),
            "speedup_pipelined_vs_serial": round(t_ser / t_pipe, 3),
            # plan-fetch overlap measurement (round-5 VERDICT item):
            # cumulative span time blocked on window plans vs inside
            # collectives, per mode
            "plan_wait_ms": {
                "pipelined": round(pw_pipe, 1),
                "serial": round(pw_ser, 1),
            },
            "exchange_ms": {
                "pipelined": round(ex_pipe, 1),
                "serial": round(ex_ser, 1),
            },
            "assembly_overlap_ratio": round(overlap_ratio, 3),
            # the pipelined run's own counters (snapshot delta), not
            # the process-cumulative totals
            "copy_bytes_avoided": pipe_delta.get(
                "exchange_copy_bytes_avoided_total", 0
            ),
            "speedup_vs_pre_pr": round(
                best_gbps / PRE_PR_REFERENCE["windowed_gbps"], 3
            ),
        },
        "pre_pr_reference": PRE_PR_REFERENCE,
    })


if __name__ == "__main__":
    main()
