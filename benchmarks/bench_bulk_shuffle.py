#!/usr/bin/env python
"""Bulk-synchronous collective shuffle end to end (readPlane=bulk).

Same record job as ``bench_collective_shuffle`` (shared workload from
benchmarks/common.py) but on the bulk-synchronous plane: the map phase
publishes normally, then ONE plan barrier + ONE symmetric
``exchange_bytes`` moves every stream (shuffle/bulk.py) — the
multi-host scaling mode.  Needs ≥4 mesh devices; on the single-chip
bench host it re-execs onto a spoofed 8-device CPU mesh, so the number
gauges the plane's overhead, not TPU silicon.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from benchmarks.common import (
        ROCE_LINE_RATE_GBPS,
        canonical_record_workload,
        emit,
        enable_metrics,
        ensure_multidevice,
        time_group_by_key,
        write_bench_json,
    )

    ensure_multidevice(__file__)

    from sparkrdma_tpu.api import TpuShuffleContext
    from sparkrdma_tpu.conf import TpuShuffleConf

    n_records, payload, n_keys = 1_000_000, 64, 512
    keys, vals = canonical_record_workload(n_records, payload, n_keys)
    conf = TpuShuffleConf()
    conf.set("serializer", "columnar")
    conf.set("readPlane", "bulk")
    conf.set("exchangeTileBytes", "16m")
    enable_metrics(conf)

    # stage_to_device pinned False on BOTH compared planes (it is now
    # the windowed/bulk default too): their exchanges read blocks
    # host-side, so HBM staging would only add a per-block device
    # round-trip; the BASELINE cross-plane ratio compares plane design
    # with identical staging either way
    with TpuShuffleContext(
        num_executors=4, conf=conf, stage_to_device=False
    ) as ctx:
        best = time_group_by_key(ctx, keys, vals, n_keys)

    gbps = n_records * payload / best / 1e9
    emit(
        f"bulk-plane groupByKey end-to-end throughput "
        f"({n_records} x {payload}B records, plan barrier + one "
        f"symmetric collective)",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )
    write_bench_json("bulk_shuffle")


if __name__ == "__main__":
    main()
