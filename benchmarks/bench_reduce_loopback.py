#!/usr/bin/env python
"""BASELINE config 2: 2-executor reduceByKey over the loopback transport,
plus the striped-fetch sweep.

The reference's second measurement config is a 2-executor
RdmaShuffleManager run with the bypass serializer (BASELINE.md).  Here:
two executor managers + a driver on the loopback network, reduceByKey
with map-side combine, raw-bytes-free int payloads.  Reported as
records/s through the full control+data plane.

The striped-fetch sweep (``BENCH_striped_fetch.json``) measures the
remote block-fetch data path over REAL sockets: stripes ∈ {1, 2, 4} ×
payload sizes, all against the single-channel pre-striping wire path
(``transportScatterGather=off``, one data lane — concat+sendall serve,
whole-frame receive) as baseline, plus RPC echo latency while bulk
reads saturate the data lanes (the head-of-line-blocking check).
"""

import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import RESULTS, emit, maybe_spoof_cpu

from sparkrdma_tpu.api import TpuShuffleContext

# BENCH_SMOKE=1: tiny tier-2 sanity config (make bench-smoke) — same
# code paths, minutes → seconds, JSON written to /tmp instead of the
# committed BENCH_*.json results
SMOKE = bool(os.environ.get("BENCH_SMOKE"))

# SPARKRDMA_TPU_BENCH_TRACE=1: run with the tracer and flight recorder
# held open and a fresh root span on every fetch — the trace-ON leg of
# the observability overhead A/B.  Traced numbers are a measurement of
# the tracer, not of the transport, so they land in /tmp and never
# overwrite the committed BENCH_*.json results.
TRACE = bool(os.environ.get("SPARKRDMA_TPU_BENCH_TRACE"))
SMOKE_DIR = "/tmp" if (SMOKE or TRACE) else None

N_RECORDS = 30_000 if SMOKE else 300_000
N_KEYS = 1024

BASE_PORT = 46300
STORE_BYTES = (4 << 20) if SMOKE else (32 << 20)
SWEEP_STRIPES = (1, 2) if SMOKE else (1, 2, 4)
SWEEP_SIZES = ((1 << 20,) if SMOKE
               else (1 << 20, 8 << 20, 32 << 20))
TARGET_MOVE = (8 << 20) if SMOKE else (192 << 20)
RPC_SAMPLES = 40 if SMOKE else 400

# fabric-scale sweep (BENCH_fabric_scale.json)
FABRIC_PEERS = (8, 32) if SMOKE else (8, 64, 256)
FABRIC_BLOCK = 256 << 10
FABRIC_CAP = 16

# decode-pipeline sweep (BENCH_decode_pipeline.json)
DECODE_THREADS = (0, 1, 2, 4)
DECODE_RECORDS = 20_000 if SMOKE else 1_500_000
DECODE_PAYLOAD = 40  # bytes per value (the classic 10-90B shuffle val)
DECODE_PARTS = 4
DECODE_REPS = 1 if SMOKE else 3


def _fetch_config(name, port, stripes, scatter_gather, extra=None):
    """One measurement config: nodes+network over real sockets, a
    registered 32 MiB store, and the per-peer read group."""
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport.node import Node

    conf_map = {
        "spark.shuffle.tpu.transportNumStripes": stripes,
        "spark.shuffle.tpu.transportStripeThreshold": "256k",
        "spark.shuffle.tpu.transportScatterGather": scatter_gather,
    }
    conf_map.update(extra or {})
    conf = TpuShuffleConf(conf_map)
    net = TcpNetwork()
    a = Node(("127.0.0.1", port), conf)
    b = Node(("127.0.0.1", port + 5), conf)
    net.register(a)
    net.register(b)
    arena = ArenaManager()
    data = (np.arange(STORE_BYTES, dtype=np.uint32) % 251).astype(np.uint8)
    seg = arena.register(data, zero_copy_ok=True)
    b.register_block_store(seg.mkey, arena)
    group = a.get_read_group(b.address, net.connect)
    return {
        "name": name, "net": net, "a": a, "b": b, "mkey": seg.mkey,
        "group": group, "arena": arena,
    }


def _teardown_config(cfg):
    cfg["a"].stop()
    cfg["b"].stop()
    cfg["net"].unregister(cfg["a"])
    cfg["net"].unregister(cfg["b"])


def _trace_ctx():
    """Fresh per-fetch root span (None when the A/B runs trace-off)."""
    if not TRACE:
        return None
    from sparkrdma_tpu.obs import TRACING

    return TRACING.start()


def _read_once(cfg, size, timeout=120):
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.utils.types import BlockLocation

    done = threading.Event()
    err = []
    cfg["group"].read_blocks(
        [BlockLocation(0, size, cfg["mkey"])],
        FnCompletionListener(
            lambda blocks: done.set(),
            lambda e: (err.append(e), done.set()),
        ),
        ctx=_trace_ctx(),
    )
    if not done.wait(timeout):
        raise RuntimeError("fetch hung")
    if err:
        raise err[0]


def _fetch_throughput(cfg, size):
    """GB/s of sequential whole-block fetches totalling TARGET_MOVE."""
    iters = max(2, TARGET_MOVE // size)
    _read_once(cfg, size)  # warmup (connects the lanes)
    t0 = time.perf_counter()
    for _ in range(iters):
        _read_once(cfg, size)
    dt = time.perf_counter() - t0
    return iters * size / dt / 1e9


def _fetch_throughput_windowed(cfg, size, window=4):
    """GB/s of WINDOWED whole-block fetches (``window`` reads in
    flight, the reader's maxBytesInFlight pipelining shape) totalling
    TARGET_MOVE — the workload the completion-driven transport core
    exists for; sequential one-at-a-time reads are latency-bound and
    measure per-read fixed hops instead."""
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.utils.types import BlockLocation

    iters = max(window, TARGET_MOVE // size)
    sem = threading.BoundedSemaphore(window)
    done = threading.Event()
    left = [iters]
    err = []
    lk = threading.Lock()

    def settle(e=None):
        if e is not None:
            err.append(e)
        sem.release()
        with lk:
            left[0] -= 1
            if left[0] == 0:
                done.set()

    _read_once(cfg, size)  # warmup (connects the lanes)
    t0 = time.perf_counter()
    for _ in range(iters):
        sem.acquire()
        cfg["group"].read_blocks(
            [BlockLocation(0, size, cfg["mkey"])],
            FnCompletionListener(
                lambda blocks: settle(), lambda e: settle(e)
            ),
            ctx=_trace_ctx(),
        )
    if not done.wait(180):
        raise RuntimeError("windowed fetch hung")
    if err:
        raise err[0]
    return iters * size / (time.perf_counter() - t0) / 1e9


def _rpc_latency_under_bulk(cfg, bulk_size=None):
    """Median RPC echo RTT (ms) while a background loop keeps bulk
    striped reads saturating the data lanes."""
    if bulk_size is None:
        bulk_size = min(8 << 20, STORE_BYTES // 4)
    from sparkrdma_tpu.transport.channel import (
        ChannelType,
        FnCompletionListener,
    )

    a, b, net = cfg["a"], cfg["b"], cfg["net"]
    pong = {"event": threading.Event()}

    def echo(channel, frame):
        channel.reply_channel().send_rpc([frame], FnCompletionListener())

    def on_pong(_channel, _frame):
        pong["event"].set()

    b.set_receive_listener(echo)
    a.set_receive_listener(on_pong)
    rpc_ch = a.get_channel(b.address, ChannelType.RPC_REQUESTOR, net.connect)
    stop = threading.Event()
    bulk_reads = [0]

    def bulk_loop():
        while not stop.is_set():
            _read_once(cfg, bulk_size)
            bulk_reads[0] += 1

    t = threading.Thread(target=bulk_loop, daemon=True)
    t.start()
    time.sleep(0.05)  # bulk in flight before sampling
    lat = []
    for _ in range(RPC_SAMPLES):
        pong["event"].clear()
        t0 = time.perf_counter()
        rpc_ch.send_rpc([b"ping"], FnCompletionListener())
        if not pong["event"].wait(10):
            raise RuntimeError("rpc echo hung under bulk load")
        lat.append((time.perf_counter() - t0) * 1000)
    stop.set()
    t.join(timeout=30)
    if bulk_reads[0] == 0:
        # an unloaded link would fake the head-of-line-blocking number
        raise RuntimeError("bulk loop made no reads during RPC sampling")
    lat.sort()
    return lat[len(lat) // 2]


def striped_fetch_sweep():
    """stripes × payload-size sweep vs the single-channel baseline;
    writes BENCH_striped_fetch.json with the metrics snapshot."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    GLOBAL_REGISTRY.enabled = True
    port = BASE_PORT
    baseline = {}
    cfg = _fetch_config("single-channel baseline", port, 1, "off")
    try:
        for size in SWEEP_SIZES:
            baseline[size] = _fetch_throughput(cfg, size)
            emit(
                f"remote fetch {size >> 20}MiB single-channel baseline "
                f"(stripes=1, scatter-gather off)",
                baseline[size], "GB/s", 1.0,
            )
        base_rpc = _rpc_latency_under_bulk(cfg)
        emit(
            "RPC echo p50 under concurrent bulk reads "
            "(single-channel baseline)",
            base_rpc, "ms", 1.0,
        )
    finally:
        _teardown_config(cfg)

    best = {"ratio": 0.0, "stripes": 1, "size": 0, "gbps": 0.0}
    rpc_striped = None
    for stripes in SWEEP_STRIPES:
        port += 20
        cfg = _fetch_config(f"stripes={stripes}", port, stripes, "on")
        try:
            for size in SWEEP_SIZES:
                gbps = _fetch_throughput(cfg, size)
                ratio = gbps / baseline[size]
                emit(
                    f"remote fetch {size >> 20}MiB stripes={stripes} "
                    f"scatter-gather",
                    gbps, "GB/s", ratio,
                )
                if ratio > best["ratio"]:
                    best.update(ratio=ratio, stripes=stripes,
                                size=size, gbps=gbps)
            if stripes == max(SWEEP_STRIPES):
                rpc_striped = _rpc_latency_under_bulk(cfg)
                emit(
                    f"RPC echo p50 under concurrent bulk reads "
                    f"(stripes={stripes})",
                    rpc_striped, "ms",
                    base_rpc / rpc_striped if rpc_striped else 1.0,
                )
        finally:
            _teardown_config(cfg)

    emit(
        f"best striped fetch vs single-channel baseline "
        f"(stripes={best['stripes']}, {best['size'] >> 20}MiB)",
        best["gbps"], "GB/s", best["ratio"],
    )
    from benchmarks.common import write_bench_json

    write_bench_json("striped_fetch", extra={
        "baseline": "single TCP data channel, scatter-gather off "
                    "(pre-striping wire path)",
        "best": best,
        "rpc_p50_ms": {"baseline": base_rpc, "striped": rpc_striped},
    }, out_dir=SMOKE_DIR)
    GLOBAL_REGISTRY.enabled = False


def async_transport_sweep():
    """Async-dispatcher vs thread-per-lane A/B on the striped-fetch
    data path, plus RPC echo p50 under concurrent bulk, plus the
    transport thread census — writes BENCH_async_transport.json with
    the threaded baseline embedded.  Absolute numbers on this host
    drift run to run; the interleaved best-of ratios are the signal."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    GLOBAL_REGISTRY.enabled = True
    port = BASE_PORT + 900
    stripes = 2
    reps = 1 if SMOKE else 3
    table = {"threaded": {}, "async": {}}
    rpc = {}
    census = {}
    # INTERLEAVED reps, best-of: this 1-core bench host is noisy
    # (run-to-run throughput swings ±20%), so each mode's number is the
    # best of `reps` alternating measurements — the same denoising the
    # decode sweep uses, applied A/B-fairly
    import threading as _th

    from sparkrdma_tpu.transport.node import TRANSPORT_THREAD_PREFIXES

    for rep in range(reps):
        for mode, flag in (("threaded", "off"), ("async", "on")):
            # census by DELTA against the threads alive before this
            # config: earlier reps leak lingering threaded-engine
            # threads (a closed listener does not wake a blocked
            # accept()), which would otherwise contaminate the async
            # engine's count with the exact threads it exists to remove
            pre = {t.ident for t in _th.enumerate()}
            cfg = _fetch_config(
                f"{mode} transport", port, stripes, "on",
                {"spark.shuffle.tpu.transportAsyncDispatcher": flag},
            )
            try:
                for size in SWEEP_SIZES:
                    gbps = _fetch_throughput_windowed(cfg, size)
                    table[mode][size] = max(
                        table[mode].get(size, 0.0), gbps
                    )
                p50 = _rpc_latency_under_bulk(cfg)
                rpc[mode] = min(rpc.get(mode, float("inf")), p50)
                by_role = {}
                for t in _th.enumerate():
                    if t.ident in pre:
                        continue
                    for prefix in TRANSPORT_THREAD_PREFIXES:
                        if t.name.startswith(prefix):
                            role = prefix.rstrip("-")
                            by_role[role] = by_role.get(role, 0) + 1
                            break
                census[mode] = {
                    "transport_threads": sum(by_role.values()),
                    "by_role": by_role,
                }
            finally:
                _teardown_config(cfg)
            port += 30
    for mode in ("threaded", "async"):
        for size in SWEEP_SIZES:
            base = table["threaded"][size]
            emit(
                f"windowed striped fetch {size >> 20}MiB "
                f"({mode} transport, stripes={stripes}, best of {reps})",
                table[mode][size], "GB/s",
                table[mode][size] / base if base else 1.0,
            )
        emit(
            f"RPC echo p50 under concurrent bulk ({mode} transport, "
            f"best of {reps})",
            rpc[mode], "ms",
            rpc["threaded"] / rpc[mode] if rpc[mode] else 1.0,
        )
    ratios = {
        size: table["async"][size] / table["threaded"][size]
        for size in SWEEP_SIZES
    }
    best_size = max(ratios, key=ratios.get)
    emit(
        f"best async-vs-threaded striped fetch ({best_size >> 20}MiB)",
        table["async"][best_size], "GB/s", ratios[best_size],
    )
    # aggregate sweep throughput (total bytes / total best-case time):
    # the single headline number the acceptance criterion reads
    agg = {
        m: sum(SWEEP_SIZES)
        / sum(size / table[m][size] for size in SWEEP_SIZES)
        for m in ("threaded", "async")
    }
    emit(
        "aggregate windowed striped-fetch throughput (async, "
        "size-weighted over sweep)",
        agg["async"], "GB/s",
        agg["async"] / agg["threaded"] if agg["threaded"] else 1.0,
    )
    from benchmarks.common import write_bench_json

    write_bench_json("async_transport", extra={
        "baseline": "transportAsyncDispatcher=off — the thread-per-"
                    "lane blocking wire path (one reader thread per "
                    "channel + accept thread + serve workers blocked "
                    "through sends)",
        "stripes": stripes,
        "fetch_gbps": {
            m: {f"{s >> 20}MiB": round(v, 4) for s, v in t.items()}
            for m, t in table.items()
        },
        "fetch_ratio_async_vs_threaded": {
            f"{s >> 20}MiB": round(r, 4) for s, r in ratios.items()
        },
        "fetch_window": 4,
        "aggregate_gbps": {m: round(v, 4) for m, v in agg.items()},
        "aggregate_ratio_async_vs_threaded": round(
            agg["async"] / agg["threaded"], 4
        ) if agg.get("threaded") else None,
        "rpc_p50_ms": {m: round(v, 4) for m, v in rpc.items()},
        "rpc_p50_ratio_threaded_over_async": round(
            rpc["threaded"] / rpc["async"], 4
        ) if rpc.get("async") else None,
        "transport_census": census,
        "host_note": (
            f"bench host has {os.cpu_count()} CPU core(s) and its "
            "absolute throughput drifts 1.5-2x between runs, so only "
            "the interleaved best-of ratios are meaningful: this run "
            "measured async/threaded fetch ratios of "
            + ", ".join(
                f"{s >> 20}MiB={ratios[s]:.2f}x" for s in SWEEP_SIZES
            )
            + f" (size-weighted aggregate "
            f"{agg['async'] / agg['threaded']:.2f}x) and RPC p50 "
            f"{rpc['async']:.3f} vs {rpc['threaded']:.3f} ms.  The "
            "async engine runs the transport on one event-loop thread "
            "+ bounded pools instead of O(peers x stripes) readers; "
            "lane streaming gives busy lanes the threaded reader's "
            "syscall shape, and the residual RPC delta is per-wake "
            "loop machinery that stops timeslicing against the peers "
            "once the host has >1 core"
        ),
    }, out_dir=SMOKE_DIR)
    GLOBAL_REGISTRY.enabled = False


def fabric_scale_sweep():
    """Dry-run connect+fetch against {8, 64, 256} simulated peers
    through the pooled fabric, bounded (transportMaxCachedChannels=16)
    vs unbounded (=0, the pre-fabric behavior) — per point: sweep wall
    time, fd/thread census, cached-channel occupancy, evictions.
    Writes BENCH_fabric_scale.json."""
    import threading as _th

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.transport.node import Node, transport_census
    from sparkrdma_tpu.transport.simfleet import SimPeerFleet
    from sparkrdma_tpu.utils.types import BlockLocation

    GLOBAL_REGISTRY.enabled = True
    pattern = (np.arange(2 << 20, dtype=np.uint32) % 251).astype(np.uint8)
    connect = TcpNetwork().connect
    port = 47000
    node_port = 46990
    table = {}

    def sweep(node, addresses, window=8):
        """One striped fetch per peer, ``window`` peers in flight (the
        reader's maxBytesInFlight shape — an unbounded burst would
        just measure the tolerated-overflow path)."""
        done_all = _th.Event()
        left = [len(addresses)]
        errs = []
        lk = _th.Lock()
        sem = _th.BoundedSemaphore(window)

        def settle(e=None):
            if e is not None:
                errs.append(e)
            sem.release()
            with lk:
                left[0] -= 1
                if left[0] == 0:
                    done_all.set()

        t0 = time.perf_counter()
        for i, peer in enumerate(addresses):
            addr = (i * 7919) % (len(pattern) - FABRIC_BLOCK)
            sem.acquire()
            node.get_read_group(peer, connect).read_blocks(
                [BlockLocation(addr, FABRIC_BLOCK, 1)],
                FnCompletionListener(
                    lambda blocks: settle(), lambda e: settle(e)
                ),
            )
        if not done_all.wait(300):
            raise RuntimeError("fabric sweep hung")
        if errs:
            raise errs[0]
        return time.perf_counter() - t0

    for n_peers in FABRIC_PEERS:
        fleet = SimPeerFleet(n_peers, port, pattern)
        port += n_peers + 16
        for mode, cap in (("unbounded", 0), ("bounded", FABRIC_CAP)):
            before = transport_census()
            ev0 = GLOBAL_REGISTRY.counter(
                "transport_channel_evictions_total").value
            node = Node(("127.0.0.1", node_port), TpuShuffleConf({
                "spark.shuffle.tpu.transportMaxCachedChannels": cap,
                "spark.shuffle.tpu.transportLanePoolSize": 8,
                "spark.shuffle.tpu.transportNumStripes": 2,
                "spark.shuffle.tpu.transportStripeThreshold": "64k",
            }))
            node_port += 1
            try:
                cold = sweep(node, fleet.addresses)
                warm = sweep(node, fleet.addresses)
                census = transport_census()
                with node._active_lock:
                    cached = len(node._active)
                point = {
                    "cold_connect_fetch_s": round(cold, 4),
                    "warm_fetch_s": round(warm, 4),
                    "fetch_mb": round(
                        n_peers * FABRIC_BLOCK / 1e6, 1),
                    "cached_channels": cached,
                    "evictions": GLOBAL_REGISTRY.counter(
                        "transport_channel_evictions_total"
                    ).value - ev0,
                    "transport_threads_grown": (
                        census["transport_threads"]
                        - before["transport_threads"]),
                    "open_fds_grown": (
                        census["open_fds"] - before["open_fds"]
                        if census["open_fds"] > 0
                        and before["open_fds"] > 0 else None),
                }
                table.setdefault(n_peers, {})[mode] = point
                emit(
                    f"fabric {n_peers} peers {mode} "
                    f"(cap={cap or 'off'}): cold connect+fetch sweep",
                    cold, "s",
                    1.0 if mode == "unbounded"
                    else table[n_peers]["unbounded"][
                        "cold_connect_fetch_s"] / cold,
                )
            finally:
                node.stop()
        fleet.close()
    from benchmarks.common import write_bench_json

    write_bench_json("fabric_scale", extra={
        "baseline": "transportMaxCachedChannels=0 — the pre-fabric "
                    "unbounded channel cache (every peer keeps its "
                    "lanes forever)",
        "block_bytes": FABRIC_BLOCK,
        "cap": FABRIC_CAP,
        "sweep": {str(k): v for k, v in table.items()},
        "note": (
            "per point: one striped 256KiB fetch per peer, cold "
            "(connect+fetch) then warm; bounded mode holds cached "
            "channels at the cap via LRU eviction while unbounded "
            "grows O(peers x lanes) — the fd/thread census per point "
            "is the scaling signal, the bounded-vs-unbounded sweep "
            "time ratio is the (small) cost of paying reconnects"
        ),
    }, out_dir=SMOKE_DIR)
    GLOBAL_REGISTRY.enabled = False


def _decode_cluster(threads, mode_conf, base_port):
    """Driver + 2 executors on loopback with the decode-pipeline conf."""
    from collections import defaultdict

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.transport import LoopbackNetwork

    net = LoopbackNetwork()
    conf_map = {
        "spark.shuffle.tpu.driverPort": base_port,
        "spark.shuffle.tpu.decodeThreads": threads,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
    }
    conf_map.update(mode_conf)
    conf = TpuShuffleConf(conf_map)
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=base_port + 20 + i * 10, executor_id=str(i),
            stage_to_device=False,
        )
        for i in range(2)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 2 for e in executors):
            break
        time.sleep(0.01)
    return net, driver, executors, defaultdict(list)


def _decode_reduce_once(threads, mode_conf, base_port, keys, vals):
    """Write the maps (untimed), then time the reduce-side consume —
    fetch + deserialize/inflate + ordered merge — across every
    partition.  Returns (best seconds, serialized bytes, output)."""
    from sparkrdma_tpu.utils.columns import ColumnBatch

    net, driver, executors, maps_by_host = _decode_cluster(
        threads, mode_conf, base_port
    )
    try:
        from sparkrdma_tpu.shuffle.partitioner import HashPartitioner

        handle = driver.register_shuffle(
            5, 2, HashPartitioner(DECODE_PARTS), key_ordering=True
        )
        n = len(keys) // 2
        total_bytes = 0
        for m, ex in enumerate(executors):
            w = ex.get_writer(handle, m)
            w.write(ColumnBatch(keys[m * n:(m + 1) * n],
                                vals[m * n:(m + 1) * n]))
            w.stop(True)
            total_bytes += w.metrics.bytes_written
            maps_by_host[ex.local_smid].append(m)
        best = float("inf")
        out = None
        for _ in range(DECODE_REPS):
            t0 = time.perf_counter()
            got = []
            for pid in range(DECODE_PARTS):
                reader = executors[pid % 2].get_reader(
                    handle, pid, pid + 1, dict(maps_by_host)
                )
                got.append(list(reader.read()))
            dt = time.perf_counter() - t0
            best = min(best, dt)
            out = got
        return best, total_bytes, out
    finally:
        for m in executors + [driver]:
            m.stop()


def decode_pipeline_sweep():
    """Decode-bound reduce sweep: compressed + columnar payloads ×
    decodeThreads {0, 1, 2, 4}, serial (decodeThreads=0, the legacy
    task-thread decode) as the embedded baseline; verifies the
    pipelined output is bit-exact against the serial one per mode.
    Writes BENCH_decode_pipeline.json."""
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    GLOBAL_REGISTRY.enabled = True
    rng = np.random.default_rng(7)
    # wide-spread int64 keys (unique with overwhelming probability →
    # fully deterministic sorted output) + incompressible payloads:
    # zlib then stores rather than squeezes, the already-compressed /
    # encrypted-shuffle shape where decode is copy- not inflate-bound
    keys = rng.permutation(DECODE_RECORDS).astype(np.int64)
    vals = np.frombuffer(
        rng.bytes(DECODE_RECORDS * DECODE_PAYLOAD),
        dtype=f"S{DECODE_PAYLOAD}",
    )
    modes = {
        "compressed-columnar": {
            "spark.shuffle.tpu.serializer": "columnar",
            "spark.shuffle.tpu.compress": True,
        },
        "columnar": {"spark.shuffle.tpu.serializer": "columnar"},
    }
    port = BASE_PORT + 400
    # warmup cluster: first-run costs (codec/native-lib loading, pool
    # page faults) must not land on the serial baseline's measurement
    _decode_reduce_once(
        0, modes["compressed-columnar"], port,
        keys[: max(DECODE_RECORDS // 20, 256)],
        vals[: max(DECODE_RECORDS // 20, 256)],
    )
    table = {}
    best = {"ratio": 0.0, "mode": "", "threads": 0, "mbps": 0.0}
    for mode, conf in modes.items():
        serial_out = None
        for threads in DECODE_THREADS:
            port += 50
            dt, nbytes, out = _decode_reduce_once(
                threads, conf, port, keys, vals
            )
            if threads == 0:
                serial_out = out
            else:
                assert out == serial_out, (
                    f"{mode}: decodeThreads={threads} output diverged "
                    f"from the serial baseline"
                )
            mbps = nbytes / dt / 1e6
            table.setdefault(mode, {})[threads] = {
                "seconds": round(dt, 4),
                "serialized_mb_per_s": round(mbps, 2),
            }
            base = table[mode][0]["serialized_mb_per_s"]
            ratio = mbps / base if base else 1.0
            emit(
                f"reduce consume {mode} decodeThreads={threads} "
                f"({DECODE_RECORDS} records, key-ordered merge)",
                mbps, "MB/s", ratio,
            )
            if threads >= 2 and ratio > best["ratio"]:
                best.update(ratio=ratio, mode=mode, threads=threads,
                            mbps=mbps)
    emit(
        f"best pipelined reduce consume vs serial-decode baseline "
        f"({best['mode']}, decodeThreads={best['threads']})",
        best["mbps"], "MB/s", best["ratio"],
    )
    from benchmarks.common import write_bench_json

    write_bench_json("decode_pipeline", extra={
        "baseline": "decodeThreads=0 — the legacy serial task-thread "
                    "decode (pre-pipeline consume path)",
        "serial_baseline": {
            m: table[m][0] for m in table
        },
        "sweep": table,
        "best_pipelined": best,
        "bit_exact": True,
        "host_note": (
            f"bench host has {os.cpu_count()} CPU core(s): with one "
            "core, decode workers can only timeslice against the task "
            "thread, so decodeThreads>=2 cannot exceed serial "
            "throughput here (the conf default therefore falls back "
            "to decodeThreads=0 on single-core hosts, the "
            "bulkPipelineWindows convention); the sweep still "
            "exercises and bit-exact-verifies the full pipelined "
            "path — fetch/decode overlap needs >=2 cores to pay"
        ),
    }, out_dir=SMOKE_DIR)
    GLOBAL_REGISTRY.enabled = False


def main():
    if TRACE:
        # hold both planes open for the whole run: every read carries a
        # live span and the recorder rings absorb the event traffic,
        # the worst-case (sampleRate=1.0) tracing cost
        from sparkrdma_tpu.obs import RECORDER, TRACING

        TRACING.retain(1.0)
        RECORDER.retain(ring_size=4096)
    maybe_spoof_cpu()
    rng = np.random.default_rng(1)
    records = [(int(k), 1) for k in rng.integers(0, N_KEYS, N_RECORDS)]

    with TpuShuffleContext(num_executors=2, stage_to_device=False) as ctx:
        ds = ctx.parallelize(records, num_slices=4)
        t0 = time.perf_counter()
        out = ds.reduce_by_key(lambda a, b: a + b, num_partitions=4).collect()
        dt = time.perf_counter() - t0

    assert len(out) == N_KEYS
    assert sum(v for _, v in out) == N_RECORDS
    rps = N_RECORDS / dt
    # no published reference number for this config (chart image only);
    # baseline ratio is vs 1M records/s, a round figure for a 2-node
    # Spark reduceByKey on the reference's hardware class
    emit(
        f"2-executor reduceByKey record throughput ({N_RECORDS} records, "
        f"{N_KEYS} keys)",
        rps / 1e6, "Mrecords/s", rps / 1e6,
    )
    from benchmarks.common import write_bench_json

    write_bench_json("reduce_loopback", out_dir=SMOKE_DIR)
    RESULTS.clear()
    striped_fetch_sweep()
    RESULTS.clear()
    decode_pipeline_sweep()
    RESULTS.clear()
    async_transport_sweep()
    RESULTS.clear()
    fabric_scale_sweep()


if __name__ == "__main__":
    main()
