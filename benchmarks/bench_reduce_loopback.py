#!/usr/bin/env python
"""BASELINE config 2: 2-executor reduceByKey over the loopback transport.

The reference's second measurement config is a 2-executor
RdmaShuffleManager run with the bypass serializer (BASELINE.md).  Here:
two executor managers + a driver on the loopback network, reduceByKey
with map-side combine, raw-bytes-free int payloads.  Reported as
records/s through the full control+data plane.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import emit, maybe_spoof_cpu

from sparkrdma_tpu.api import TpuShuffleContext

N_RECORDS = 300_000
N_KEYS = 1024


def main():
    maybe_spoof_cpu()
    rng = np.random.default_rng(1)
    records = [(int(k), 1) for k in rng.integers(0, N_KEYS, N_RECORDS)]

    with TpuShuffleContext(num_executors=2, stage_to_device=False) as ctx:
        ds = ctx.parallelize(records, num_slices=4)
        t0 = time.perf_counter()
        out = ds.reduce_by_key(lambda a, b: a + b, num_partitions=4).collect()
        dt = time.perf_counter() - t0

    assert len(out) == N_KEYS
    assert sum(v for _, v in out) == N_RECORDS
    rps = N_RECORDS / dt
    # no published reference number for this config (chart image only);
    # baseline ratio is vs 1M records/s, a round figure for a 2-node
    # Spark reduceByKey on the reference's hardware class
    emit(
        f"2-executor reduceByKey record throughput ({N_RECORDS} records, "
        f"{N_KEYS} keys)",
        rps / 1e6, "Mrecords/s", rps / 1e6,
    )
    from benchmarks.common import write_bench_json

    write_bench_json("reduce_loopback")


if __name__ == "__main__":
    main()
