#!/usr/bin/env python
"""Integrated record-plane shuffle over the UNIFIED device plane.

BASELINE config 2's round-4 form: the same groupByKey record job as
``bench_local_baseline`` (shared workload from benchmarks/common.py),
but every byte moving via driver-planned window collectives
(readPlane=windowed, shuffle/bulk.py WindowedReadPlane) — the write →
publish → plan windows → TileExchange → reducer reads integration
standing in for the reference's commit → publish → FetchMapStatus →
scatter RDMA READ pipeline (RdmaShuffleFetcherIterator.scala:162-171,
RdmaChannel.java:441-474).  Supersedes the round-2/3 coordinator
variant (tests/collective_read_fixture.py, now a test fixture).

Needs ≥4 mesh devices; on the single-chip bench host it re-execs onto
a spoofed 8-device CPU mesh, so the number gauges the integrated
plane's overhead, not TPU silicon.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from benchmarks.common import (
        ROCE_LINE_RATE_GBPS,
        canonical_record_workload,
        emit,
        ensure_multidevice,
        time_group_by_key,
    )

    ensure_multidevice(__file__)

    from sparkrdma_tpu.api import TpuShuffleContext
    from sparkrdma_tpu.conf import TpuShuffleConf

    n_records, payload, n_keys = 1_000_000, 64, 512
    keys, vals = canonical_record_workload(n_records, payload, n_keys)
    conf = TpuShuffleConf()
    conf.set("serializer", "columnar")
    conf.set("readPlane", "windowed")
    # bulkWindowMaps trades throughput for straggler overlap: each plan
    # window is one collective (its own dispatch + tile padding).  The
    # throughput configuration is a single window (0); measured on the
    # 8-device CPU mesh: wm=0 0.122 GB/s, wm=4 0.060, wm=2 0.035 —
    # overlap-hungry jobs pick fine windows, throughput jobs coarse.
    # SPARKRDMA_BENCH_WINDOW_MAPS gauges the fine-window settings.
    conf.set("bulkWindowMaps",
             os.environ.get("SPARKRDMA_BENCH_WINDOW_MAPS", "0"))
    conf.set("exchangeTileBytes", "16m")

    # staging pinned False to match bench_bulk_shuffle (like-for-like)
    with TpuShuffleContext(
        num_executors=4, conf=conf, stage_to_device=False
    ) as ctx:
        best = time_group_by_key(ctx, keys, vals, n_keys)
        stats = ctx.executors[0].windowed_plane.stats()
        assert stats["rounds_executed"] > 0, "windowed plane never ran"
        assert stats["payload_bytes_moved"] > 0, "no payload exchanged"

    gbps = n_records * payload / best / 1e9
    emit(
        f"windowed-plane groupByKey end-to-end throughput "
        f"({n_records} x {payload}B records, plan windows + "
        f"all_to_all rounds)",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
