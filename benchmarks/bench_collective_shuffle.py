#!/usr/bin/env python
"""Integrated record-plane shuffle over the COLLECTIVE read plane.

BASELINE config 2's round-2 form: the same groupByKey/reduceByKey record
job as ``bench_local_baseline``, but with map outputs committed into
per-device HBM arenas and every remote fetch executed as pack +
``all_to_all`` tile rounds over the mesh (parallel/collective_read.py) —
the write → publish → resolve → exchange → read integration standing in
for the reference's commit → publish → FetchMapStatus → scatter RDMA
READ pipeline (RdmaShuffleFetcherIterator.scala:162-171,
RdmaChannel.java:441-474).

Needs ≥4 mesh devices; on the single-chip bench host it re-execs itself
onto a spoofed 8-device CPU mesh (the same harness the test suite and
the driver's dryrun use), so the number gauges the integrated plane's
overhead, not TPU silicon.
"""

import os
import subprocess
import sys

_SPOOF_ENV = "SPARKRDMA_TPU_BENCH_SPOOFED"


def _respawn_spoofed() -> int:
    env = dict(os.environ)
    env[_SPOOF_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return subprocess.call([sys.executable, os.path.abspath(__file__)], env=env)


def main():
    import time

    import jax
    import numpy as np

    if os.environ.get(_SPOOF_ENV):
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < 4:
        if os.environ.get(_SPOOF_ENV):
            raise RuntimeError("spoofed respawn still has <4 devices")
        sys.exit(_respawn_spoofed())

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from benchmarks.common import ROCE_LINE_RATE_GBPS, emit

    from sparkrdma_tpu.api import TpuShuffleContext
    from sparkrdma_tpu.conf import TpuShuffleConf

    n_records = 1_000_000
    payload = 64
    n_keys = 512
    reps = 3

    rng = np.random.default_rng(0)
    keys = rng.integers(0, n_keys, n_records).astype(np.int64)
    vals = np.frombuffer(rng.bytes(n_records * payload), dtype=f"S{payload}")
    conf = TpuShuffleConf()
    conf.set("serializer", "columnar")
    conf.set("readPlane", "collective")
    conf.set("deviceArenaBytes", 256 << 20)
    # collective tile rounds amortize over LARGE grouped fetches: widen
    # the reference's NIC-era defaults (256k groups / 1m window)
    conf.set("shuffleReadBlockSize", "32m")
    conf.set("maxAggBlock", "32m")
    conf.set("maxBytesInFlight", "128m")
    conf.set("exchangeTileBytes", "16m")
    conf.set("exchangeFlush", "10ms")

    with TpuShuffleContext(num_executors=4, conf=conf) as ctx:
        ds = ctx.parallelize_columns(keys, vals, num_slices=8)
        out = ds.group_by_key(num_partitions=8).collect()  # warm + check
        assert len(out) == n_keys, f"expected {n_keys} groups, got {len(out)}"
        assert sum(len(vs) for _, vs in out) == n_records
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            ds.group_by_key(num_partitions=8).collect()
            best = min(best, time.perf_counter() - t0)
        stats = ctx.network.coordinator.stats()
        assert stats["rounds_executed"] > 0, "collective plane never ran"
        assert stats["fallback_blocks"] == 0, "collective plane fell back"

    gbps = n_records * payload / best / 1e9
    emit(
        f"collective-plane groupByKey end-to-end throughput "
        f"({n_records} x {payload}B records, arena + all_to_all rounds)",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
