#!/usr/bin/env python
"""Integrated record-plane shuffle over the COLLECTIVE read plane.

BASELINE config 2's round-2 form: the same groupByKey record job as
``bench_local_baseline`` (shared workload from benchmarks/common.py),
but with map outputs committed into per-device HBM arenas and every
remote fetch executed as pack + ``all_to_all`` tile rounds over the
mesh (parallel/collective_read.py) — the write → publish → resolve →
exchange → read integration standing in for the reference's commit →
publish → FetchMapStatus → scatter RDMA READ pipeline
(RdmaShuffleFetcherIterator.scala:162-171, RdmaChannel.java:441-474).

Needs ≥4 mesh devices; on the single-chip bench host it re-execs onto
a spoofed 8-device CPU mesh, so the number gauges the integrated
plane's overhead, not TPU silicon.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from benchmarks.common import (
        ROCE_LINE_RATE_GBPS,
        canonical_record_workload,
        emit,
        ensure_multidevice,
        time_group_by_key,
    )

    ensure_multidevice(__file__)

    from sparkrdma_tpu.api import TpuShuffleContext
    from sparkrdma_tpu.conf import TpuShuffleConf

    n_records, payload, n_keys = 1_000_000, 64, 512
    keys, vals = canonical_record_workload(n_records, payload, n_keys)
    conf = TpuShuffleConf()
    conf.set("serializer", "columnar")
    conf.set("readPlane", "collective")
    conf.set("deviceArenaBytes", 256 << 20)
    # collective tile rounds amortize over LARGE grouped fetches: widen
    # the reference's NIC-era defaults (256k groups / 1m window)
    conf.set("shuffleReadBlockSize", "32m")
    conf.set("maxAggBlock", "32m")
    conf.set("maxBytesInFlight", "128m")
    conf.set("exchangeTileBytes", "16m")
    conf.set("exchangeFlush", "10ms")

    with TpuShuffleContext(num_executors=4, conf=conf) as ctx:
        best = time_group_by_key(ctx, keys, vals, n_keys)
        stats = ctx.network.coordinator.stats()
        assert stats["rounds_executed"] > 0, "collective plane never ran"
        assert stats["fallback_blocks"] == 0, "collective plane fell back"

    gbps = n_records * payload / best / 1e9
    emit(
        f"collective-plane groupByKey end-to-end throughput "
        f"({n_records} x {payload}B records, arena + all_to_all rounds)",
        gbps, "GB/s", gbps / ROCE_LINE_RATE_GBPS,
    )


if __name__ == "__main__":
    main()
