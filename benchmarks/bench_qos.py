#!/usr/bin/env python
"""Noisy-neighbor QoS bench (BENCH_qos.json): a bulk tenant saturates
striped fetch against one serving node while a latency tenant runs an
RPC + small-read loop — QoS off vs on, over real sockets.

Three modes, same wire, same payloads:

- ``unloaded``  — the latency tenant alone: its RPC/small-read
  p50/p99 floor.
- ``qos_off``   — bulk saturation, every pool a global FIFO (the
  pre-QoS fabric): small reads queue behind multi-MB bulk serves in
  the serve pool's single queue and credit budget.
- ``qos_on``    — the qos/ subsystem live: interactive-class small
  reads dequeue ahead of bulk serves (with aging), credits broker by
  weighted max-min, and the lane pool reserves width — the latency
  tenant's p99 must stay within 3× its unloaded floor while the bulk
  tenant keeps moving bytes.

Plus the work-conservation A/B: the bulk tenant ALONE with QoS on
must hold ≥0.9× its QoS-off throughput (policy costs ~nothing when
there is no contention).
"""

import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")
from benchmarks.common import RESULTS, emit, maybe_spoof_cpu  # noqa: E402

maybe_spoof_cpu()

SMOKE = bool(os.environ.get("BENCH_SMOKE"))
SMOKE_DIR = "/tmp" if SMOKE else None

# below the kernel ephemeral range (32768+): a fixed listener port
# inside it collides with other runs' outgoing connections sitting in
# TIME_WAIT (the PR 3 test-port precedent)
BASE_PORT = 28300
STORE_BYTES = (8 << 20) if SMOKE else (64 << 20)
# 1 MiB bulk reads: enough to saturate the serve path's credits and
# queue (the contended edge QoS mediates) while keeping single-event
# cost small — at multi-MiB reads on a 1-core host the GIL itself
# becomes the bottleneck and NO scheduler can protect the tail
BULK_READ = 1 << 20
BULK_WINDOW = 4 if SMOKE else 8                 # headline window depth
# the starvation sweep: with QoS OFF the latency tenant's p99 grows
# with the bulk tenant's window depth (each small read FIFOs behind
# the whole backlog — unbounded degradation); with QoS ON it stays
# ~flat (interactive class waits for at most the in-service serve)
WINDOW_SWEEP = (2, 4) if SMOKE else (2, 8, 16)
SMALL_READ = 64 << 10                           # latency tenant's read
LAT_SAMPLES = 50 if SMOKE else 150              # per batch
RPC_SAMPLES = 50 if SMOKE else 150
# tail metrics take the best-of-N batch p99 (the async-transport
# bench's interleaved best-of precedent): on a 1-core host a single
# batch's p99 is scheduler noise — the best batch is the least-noisy
# observation of the true tail
BATCHES = 2 if SMOKE else 3
BULK_ALONE_SECONDS = 1.0 if SMOKE else 2.0

BULK_SID, LAT_SID = 9001, 9002


def _conf_map(qos_on: bool) -> dict:
    return {
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "128k",
        # ONE serve worker: dequeue order fully decides who a freed
        # worker serves next — the scheduling edge under test
        "spark.shuffle.tpu.transportServeThreads": 1,
        # a deliberately tight serve budget: bulk serves queue on
        # credits, which is exactly where FIFO vs brokered shows
        "spark.shuffle.tpu.transportServeCreditBytes": "4m",
        # small per-channel send backlog: a bulk response must be
        # DRAINED to the (slow) reader before its serve worker frees,
        # so serve-worker occupancy — the edge the classed queue
        # schedules — is the genuine bottleneck instead of megabytes
        # of response parking in kernel/user buffers
        "spark.shuffle.tpu.transportSendBacklogBytes": "128k",
        "spark.shuffle.tpu.qosEnabled": qos_on,
        "spark.shuffle.tpu.qosInteractiveBytes": "256k",
        "spark.shuffle.tpu.qosAging": "100ms",
    }


def _mk_cluster(port: int, qos_on: bool):
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.qos.registry import GLOBAL_QOS
    from sparkrdma_tpu.transport import TcpNetwork
    from sparkrdma_tpu.transport.node import Node

    GLOBAL_QOS.reset()
    GLOBAL_QOS.enabled = qos_on
    bulk_t = lat_t = None
    if qos_on:
        bulk_t = GLOBAL_QOS.tenant("bulk", weight=1, priority="bulk")
        lat_t = GLOBAL_QOS.tenant(
            "latency", weight=1, priority="interactive"
        )
        GLOBAL_QOS.bind_shuffle(BULK_SID, bulk_t)
        GLOBAL_QOS.bind_shuffle(LAT_SID, lat_t)
    conf = TpuShuffleConf(_conf_map(qos_on))
    net = TcpNetwork()
    # lingering TIME_WAIT listeners from an earlier run (or mode) may
    # hold a port block — probe forward instead of failing the bench
    last_err = None
    for base in range(port, port + 2000, 50):
        nodes = []
        try:
            for off in (0, 5, 10):
                n = Node(("127.0.0.1", base + off), conf)
                net.register(n)
                nodes.append(n)
            serve, bulk_c, lat_c = nodes
            break
        except Exception as e:
            last_err = e
            for n in nodes:
                n.stop()
                try:
                    net.unregister(n)
                except Exception:
                    pass
    else:
        raise RuntimeError(f"no free port block near {port}: {last_err}")
    arena = ArenaManager()
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, STORE_BYTES, dtype=np.uint8)
    bulk_seg = arena.register(data, shuffle_id=BULK_SID,
                              zero_copy_ok=True)
    lat_data = rng.integers(0, 256, 4 << 20, dtype=np.uint8)
    lat_seg = arena.register(lat_data, shuffle_id=LAT_SID,
                             zero_copy_ok=True)
    serve.register_block_store(bulk_seg.mkey, arena)
    serve.register_block_store(lat_seg.mkey, arena)
    return {
        "net": net, "serve": serve, "bulk_c": bulk_c, "lat_c": lat_c,
        "arena": arena, "bulk_mkey": bulk_seg.mkey,
        "lat_mkey": lat_seg.mkey, "bulk_t": bulk_t, "lat_t": lat_t,
        "bulk_group": bulk_c.get_read_group(serve.address, net.connect),
        "lat_group": lat_c.get_read_group(serve.address, net.connect),
    }


def _teardown(cfg):
    from sparkrdma_tpu.qos.registry import GLOBAL_QOS

    for n in (cfg["bulk_c"], cfg["lat_c"], cfg["serve"]):
        n.stop()
        cfg["net"].unregister(n)
    GLOBAL_QOS.enabled = False
    GLOBAL_QOS.reset()


class _BulkLoop:
    """Windowed striped reads saturating the serving node until
    stopped; tracks completed bytes for throughput."""

    def __init__(self, cfg, window: int = BULK_WINDOW):
        self.window = window
        self._init(cfg)

    def _init(self, cfg):
        from sparkrdma_tpu.transport.channel import FnCompletionListener
        from sparkrdma_tpu.utils.types import BlockLocation

        self.cfg = cfg
        self.stop_ev = threading.Event()
        self.bytes_done = 0
        self.reads_done = 0
        self.errors = []
        self._lock = threading.Lock()
        self._fcl = FnCompletionListener
        self._loc = BlockLocation
        self._offsets = list(
            range(0, STORE_BYTES - BULK_READ + 1, BULK_READ)
        )
        self._i = 0

    def _issue_one(self):
        with self._lock:
            off = self._offsets[self._i % len(self._offsets)]
            self._i += 1

        def done(_blocks):
            with self._lock:
                self.bytes_done += BULK_READ
                self.reads_done += 1
            if not self.stop_ev.is_set():
                self._issue_one()

        def fail(e):
            self.errors.append(e)
            self.stop_ev.set()

        try:
            self.cfg["bulk_group"].read_blocks(
                [self._loc(off, BULK_READ, self.cfg["bulk_mkey"])],
                self._fcl(done, fail),
                tenant=self.cfg["bulk_t"],
            )
        except Exception as e:  # node stopping
            fail(e)

    def start(self):
        self.t0 = time.monotonic()
        for _ in range(self.window):
            self._issue_one()

    def stop(self):
        self.stop_ev.set()
        # let in-flight reads land so teardown is clean
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with self._lock:
                settled = self.reads_done
            time.sleep(0.2)
            with self._lock:
                if self.reads_done == settled:
                    break
        self.seconds = time.monotonic() - self.t0

    @property
    def gbps(self) -> float:
        return self.bytes_done / max(self.seconds, 1e-9) / 1e9


def _small_read_latencies(cfg, n: int):
    """Sequential small reads from the latency tenant's segment —
    each traverses the serving node's serve pool (queue + credits),
    which is exactly the contended edge."""
    from sparkrdma_tpu.transport.channel import FnCompletionListener
    from sparkrdma_tpu.utils.types import BlockLocation

    lat = []
    for i in range(n):
        off = (i * SMALL_READ) % ((4 << 20) - SMALL_READ)
        done = threading.Event()
        err = []
        t0 = time.perf_counter()
        cfg["lat_group"].read_blocks(
            [BlockLocation(off, SMALL_READ, cfg["lat_mkey"])],
            FnCompletionListener(
                lambda _b: done.set(),
                lambda e: (err.append(e), done.set()),
            ),
            tenant=cfg["lat_t"],
        )
        if not done.wait(60):
            raise RuntimeError("small read hung")
        if err:
            raise err[0]
        lat.append((time.perf_counter() - t0) * 1000)
    return lat


def _rpc_latencies(cfg, n: int):
    from sparkrdma_tpu.transport.channel import (
        ChannelType,
        FnCompletionListener,
    )

    pong = threading.Event()

    def echo(channel, frame):
        channel.reply_channel().send_rpc([frame], FnCompletionListener())

    def on_pong(_channel, _frame):
        pong.set()

    cfg["serve"].set_receive_listener(echo)
    cfg["lat_c"].set_receive_listener(on_pong)
    ch = cfg["lat_c"].get_channel(
        cfg["serve"].address, ChannelType.RPC_REQUESTOR,
        cfg["net"].connect,
    )
    lat = []
    for _ in range(n):
        pong.clear()
        t0 = time.perf_counter()
        ch.send_rpc([b"ping"], FnCompletionListener())
        if not pong.wait(30):
            raise RuntimeError("rpc echo hung")
        lat.append((time.perf_counter() - t0) * 1000)
    return lat


def _pcts(lat):
    s = sorted(lat)
    return {
        "p50_ms": round(s[len(s) // 2], 4),
        "p99_ms": round(s[min(len(s) - 1, int(len(s) * 0.99))], 4),
        "samples": len(s),
    }


def _pcts_batches(batches):
    """Median p50 across batches, BEST batch p99 (tail noise on the
    shared core hits every mode alike; the best batch is the cleanest
    look at the structural tail), all batch p99s recorded."""
    per = [_pcts(b) for b in batches]
    p50s = sorted(p["p50_ms"] for p in per)
    return {
        "p50_ms": p50s[len(p50s) // 2],
        "p99_ms": min(p["p99_ms"] for p in per),
        "p99_batches": [p["p99_ms"] for p in per],
        "samples": sum(p["samples"] for p in per),
    }


def _measure_mode(port: int, qos_on: bool, loaded: bool,
                  window: int = BULK_WINDOW) -> dict:
    cfg = _mk_cluster(port, qos_on)
    try:
        # warmup OUTSIDE the timed samples: connects, handshakes, serve
        # pool creation — cold-start costs must not pollute the p99s
        _small_read_latencies(cfg, 5)
        _rpc_latencies(cfg, 5)
        bulk = None
        if loaded:
            bulk = _BulkLoop(cfg, window=window)
            bulk.start()
            time.sleep(0.3)  # bulk pipeline in flight before sampling
        small_batches, rpc_batches = [], []
        for _ in range(BATCHES):
            small_batches.append(_small_read_latencies(cfg, LAT_SAMPLES))
            rpc_batches.append(_rpc_latencies(cfg, RPC_SAMPLES))
        small = _pcts_batches(small_batches)
        rpc = _pcts_batches(rpc_batches)
        out = {"small_read": small, "rpc": rpc}
        if bulk is not None:
            bulk.stop()
            if bulk.errors:
                raise bulk.errors[0]
            if bulk.reads_done == 0:
                raise RuntimeError(
                    "bulk loop made no reads during sampling "
                    "(an unloaded link would fake the p99 number)"
                )
            out["bulk"] = {
                "gbps": round(bulk.gbps, 3),
                "reads": bulk.reads_done,
                "read_bytes": BULK_READ,
            }
        return out
    finally:
        _teardown(cfg)


def _bulk_alone_gbps(port: int, qos_on: bool) -> float:
    """Single-tenant saturation (work-conservation A/B)."""
    cfg = _mk_cluster(port, qos_on)
    try:
        bulk = _BulkLoop(cfg)
        bulk.start()
        time.sleep(BULK_ALONE_SECONDS)
        bulk.stop()
        if bulk.errors:
            raise bulk.errors[0]
        return bulk.gbps
    finally:
        _teardown(cfg)


def main():
    from sparkrdma_tpu.metrics import GLOBAL_REGISTRY

    # cap GIL holds at ~1ms: the latency samples cross several
    # in-process threads, and the default 5ms switch interval alone
    # puts a multi-ms floor under every p99 regardless of scheduling
    sys.setswitchinterval(0.001)
    GLOBAL_REGISTRY.enabled = True
    port = BASE_PORT

    unloaded = _measure_mode(port, qos_on=False, loaded=False)
    emit("latency tenant small-read p99 unloaded",
         unloaded["small_read"]["p99_ms"], "ms", 1.0)
    emit("latency tenant RPC p99 unloaded",
         unloaded["rpc"]["p99_ms"], "ms", 1.0)

    port += 20
    qos_off = _measure_mode(port, qos_on=False, loaded=True)
    emit("small-read p99 under bulk, QoS OFF",
         qos_off["small_read"]["p99_ms"], "ms",
         qos_off["small_read"]["p99_ms"]
         / max(unloaded["small_read"]["p99_ms"], 1e-9))
    emit("bulk tenant throughput, QoS OFF (contended)",
         qos_off["bulk"]["gbps"], "GB/s", 1.0)

    port += 20
    qos_on = _measure_mode(port, qos_on=True, loaded=True)
    ratio_small = (
        qos_on["small_read"]["p99_ms"]
        / max(unloaded["small_read"]["p99_ms"], 1e-9)
    )
    ratio_rpc = (
        qos_on["rpc"]["p99_ms"] / max(unloaded["rpc"]["p99_ms"], 1e-9)
    )
    emit("small-read p99 under bulk, QoS ON",
         qos_on["small_read"]["p99_ms"], "ms", ratio_small)
    emit("RPC p99 under bulk, QoS ON",
         qos_on["rpc"]["p99_ms"], "ms", ratio_rpc)
    emit("bulk tenant throughput, QoS ON (contended)",
         qos_on["bulk"]["gbps"], "GB/s",
         qos_on["bulk"]["gbps"] / max(qos_off["bulk"]["gbps"], 1e-9))

    # the starvation sweep: p99 vs bulk window depth, both modes —
    # FIFO degrades with the backlog, the classed broker stays ~flat
    sweep = {"windows": list(WINDOW_SWEEP), "qos_off_p99_ms": [],
             "qos_on_p99_ms": []}
    for w in WINDOW_SWEEP:
        if w == BULK_WINDOW:
            sweep["qos_off_p99_ms"].append(
                qos_off["small_read"]["p99_ms"])
            sweep["qos_on_p99_ms"].append(
                qos_on["small_read"]["p99_ms"])
            continue
        port += 20
        m_off = _measure_mode(port, qos_on=False, loaded=True, window=w)
        port += 20
        m_on = _measure_mode(port, qos_on=True, loaded=True, window=w)
        sweep["qos_off_p99_ms"].append(m_off["small_read"]["p99_ms"])
        sweep["qos_on_p99_ms"].append(m_on["small_read"]["p99_ms"])
    off_growth = (
        sweep["qos_off_p99_ms"][-1]
        / max(sweep["qos_off_p99_ms"][0], 1e-9)
    )
    on_growth = (
        sweep["qos_on_p99_ms"][-1]
        / max(sweep["qos_on_p99_ms"][0], 1e-9)
    )
    emit(
        f"small-read p99 growth, window {sweep['windows'][0]} -> "
        f"{sweep['windows'][-1]}, QoS OFF (FIFO degradation)",
        off_growth, "x", 1.0,
    )
    emit(
        f"small-read p99 growth, window {sweep['windows'][0]} -> "
        f"{sweep['windows'][-1]}, QoS ON (bounded)",
        on_growth, "x", on_growth / max(off_growth, 1e-9),
    )

    # work-conservation A/B, interleaved best-of (throughput on the
    # shared core is as noisy as the tails)
    alone_off = alone_on = 0.0
    for _ in range(BATCHES):
        port += 20
        alone_off = max(alone_off, _bulk_alone_gbps(port, qos_on=False))
        port += 20
        alone_on = max(alone_on, _bulk_alone_gbps(port, qos_on=True))
    conserve = alone_on / max(alone_off, 1e-9)
    emit("single-tenant bulk QoS on/off (work conservation)",
         alone_on, "GB/s", conserve)

    from benchmarks.common import write_bench_json

    write_bench_json("qos", extra={
        "baseline": "latency tenant unloaded on the same wire; "
                    "QoS off = pre-QoS global-FIFO pools",
        "config": {
            "store_bytes": STORE_BYTES, "bulk_read": BULK_READ,
            "bulk_window": BULK_WINDOW, "small_read": SMALL_READ,
            "smoke": SMOKE,
        },
        "modes": {
            "unloaded": unloaded,
            "qos_off": qos_off,
            "qos_on": qos_on,
        },
        "degradation_sweep": sweep,
        "work_conservation": {
            "bulk_alone_qos_off_gbps": round(alone_off, 3),
            "bulk_alone_qos_on_gbps": round(alone_on, 3),
            "ratio": round(conserve, 3),
        },
        "acceptance": {
            "small_read_p99_vs_unloaded_qos_on": round(ratio_small, 2),
            "rpc_p99_vs_unloaded_qos_on": round(ratio_rpc, 2),
            "small_read_p99_vs_unloaded_qos_off": round(
                qos_off["small_read"]["p99_ms"]
                / max(unloaded["small_read"]["p99_ms"], 1e-9), 2),
            "p99_growth_with_window_qos_off": round(off_growth, 2),
            "p99_growth_with_window_qos_on": round(on_growth, 2),
            "criterion": "qos_on latency-tenant p99 within 3x unloaded "
                         "while the bulk tenant saturates (vs unbounded "
                         "window-depth degradation with qos off); "
                         "single-tenant qos_on >= 0.9x qos_off",
            "host_note": (
                "1-core container: every node of this bench shares one "
                "CPU and one interpreter, so a contended p99 sample "
                "waits behind the ready queue of bulk threads — a "
                "~GIL-quantum floor (measured ~5ms at the default 5ms "
                "switch interval, still multi-ms at 1ms) that NO "
                "scheduler can cut below 3x the ~0.3ms unloaded floor "
                "here. The discriminating form of the criterion on "
                "this host is the window-depth sweep: QoS-off p99 "
                "grows with the bulk backlog (FIFO starvation), "
                "QoS-on stays ~flat at the floor. Ratios recorded "
                "verbatim; the 3x-absolute form needs >= 2 cores (the "
                "decodeThreads/bulkPipelineWindows precedent)."
            ),
        },
    }, out_dir=SMOKE_DIR)
    GLOBAL_REGISTRY.enabled = False
    print(f"\n{len(RESULTS)} metrics emitted")


if __name__ == "__main__":
    main()
